"""Cross-host fleet suite (kindel_tpu.fleet.rpc / .procreplica):
DESIGN.md §21's claims, asserted.

  * the network fault family is the wire-level sibling of PR 4's —
    refused/timeout/slow/drop_response/garbage/reset parse, fire
    deterministically, and carry the transient-classifier vocabulary;
  * `RpcServiceClient` implements the SAME service contract as the
    in-process replica service: a shared parametrized suite walks a
    Replica through probe/submit/kill/drain against both backends;
  * idempotency: a response lost AFTER the server applied the request
    (`rpc.call:drop_response`) is resubmitted under the same key and
    deduped server-side — applied once, settled exactly once,
    byte-identical FASTA;
  * one trace covers router → wire → remote worker → device dispatch
    (deterministic JSONL span-tree, PR 3 style);
  * the HTTP front refuses oversized bodies with 413 + Retry-After
    before any allocation (`--max-body-mb` through tune.py);
  * the autoscaler scales up on sustained watermark sheds, scales down
    by draining the lowest-occupancy replica, and its hysteresis is
    pinned: a square-wave load cannot flap the fleet;
  * the flagship: 3 replica PROCESSES under injected network faults,
    one SIGKILLed and another autoscale-drained mid-load — every
    admitted future settled exactly once, FASTA sha256 identical to a
    single-replica in-process run, the killed slot respawned as a
    fresh process that serves again.
"""

import json
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path
from types import SimpleNamespace

import pytest

from kindel_tpu.fleet import FleetRouter, FleetService, Replica, routing_key
from kindel_tpu.fleet.rpc import (
    IDEMPOTENCY_HEADER,
    IdempotencyCache,
    RpcGarbageResponse,
    RpcServerAdapter,
    RpcServiceClient,
    RpcTransportError,
    wire_transient,
)
from kindel_tpu.fleet.supervisor import FleetAutoscaler
from kindel_tpu.io.fasta import Sequence, format_fasta, parse_fasta
from kindel_tpu.obs import trace
from kindel_tpu.obs.metrics import default_registry
from kindel_tpu.resilience import faults as rfaults
from kindel_tpu.resilience import policy as rpolicy
from kindel_tpu.resilience.faults import GARBAGE_BYTES, FaultPlan
from kindel_tpu.resilience.policy import RetryPolicy
from kindel_tpu.serve.metrics import MetricsRegistry, ServeHTTPServer
from kindel_tpu.serve.queue import (
    AdmissionError,
    DeadlineExceeded,
    ServiceDegraded,
)


@pytest.fixture(autouse=True)
def _clean_fault_state():
    """Process-global fault plans / policies / tracers must not leak
    (same hygiene as test_resilience.py)."""
    rfaults.deactivate()
    prev = rpolicy.set_default_policy(None)
    yield
    rfaults.deactivate()
    rpolicy.set_default_policy(prev)
    trace.disable_tracing()


def _fleet_delta(before: dict, after: dict, name: str) -> int:
    return int(after.get(name, 0)) - int(before.get(name, 0))


# ------------------------------------------------ network fault family


def test_network_fault_specs_parse_and_fire():
    plan = FaultPlan.parse(
        "seed=3,rpc.connect:refused,rpc.call:drop_response:times=2,"
        "rpc.call:garbage:after=2,rpc.probe:reset,rpc.call:timeout:after=3"
    )
    with pytest.raises(rfaults.InjectedFault) as exc:
        plan.fire("rpc.connect")
    assert "refused" in str(exc.value) and "UNAVAILABLE" in str(exc.value)
    # drop_response fires on the bytes hook (response in hand)
    for _ in range(2):
        with pytest.raises(rfaults.InjectedFault) as exc:
            plan.filter_bytes("rpc.call", b">x\nACGT\n")
        assert exc.value.kind == "drop_response"
    # hit 3: garbage substitutes the deterministic corruption
    assert plan.filter_bytes("rpc.call", b">x\nACGT\n") == GARBAGE_BYTES
    # hit 4: timeout carries the deadline vocabulary
    with pytest.raises(rfaults.InjectedFault) as exc:
        plan.filter_bytes("rpc.call", b">x\n")
    assert "DEADLINE_EXCEEDED" in str(exc.value)
    # probes have their own site — the call specs did not consume it
    with pytest.raises(rfaults.InjectedFault) as exc:
        plan.filter_bytes("rpc.probe", b"{}")
    assert "Connection reset" in str(exc.value)
    assert plan.fired == {
        ("rpc.connect", "refused"): 1,
        ("rpc.call", "drop_response"): 2,
        ("rpc.call", "garbage"): 1,
        ("rpc.call", "timeout"): 1,
        ("rpc.probe", "reset"): 1,
    }


def test_network_faults_classify_as_wire_transient():
    plan = FaultPlan.parse(
        "rpc.connect:refused,rpc.call:reset,rpc.probe:drop_response"
    )
    for site in ("rpc.connect", "rpc.call", "rpc.probe"):
        with pytest.raises(rfaults.InjectedFault) as exc:
            plan.fire(site)
        assert wire_transient(exc.value), exc.value
    assert wire_transient(RpcGarbageResponse("mangled"))
    assert wire_transient(ConnectionRefusedError("dial"))
    assert not wire_transient(KeyError("request-level bug"))


def test_slow_kind_injects_latency_without_failing():
    slept = []
    plan = FaultPlan(
        [rfaults.FaultSpec("rpc.call", "slow", delay_s=0.125)],
        sleep=slept.append,
    )
    assert plan.filter_bytes("rpc.call", b"ok") == b"ok"
    assert slept == [0.125]


# ------------------------------------------- stub remote + HTTP server


class _StubRemote:
    """A ConsensusService-shaped stub the RpcServerAdapter wraps: real
    enough for the wire (records → FASTA via the real response path),
    no device anywhere. `mode` selects the behavior; `applied` counts
    actual request applications (the at-most-once assertion)."""

    def __init__(self):
        self.mode = "ok"
        self.records = [Sequence("stub1", "ACGTACGT")]
        self.applied = 0
        self.apply_delay_s = 0.0
        self.seen_opts: list = []
        self.drained: list = []
        self.live = True
        self.queue_depth = 0
        self.watermark = 64

    def request(self, payload, deadline_s=None, **opts):
        self.applied += 1
        self.seen_opts.append(dict(opts, deadline_s=deadline_s))
        if self.apply_delay_s:
            time.sleep(self.apply_delay_s)
        if self.mode == "shed":
            raise AdmissionError("stub watermark", 0.2)
        if self.mode == "degraded":
            raise ServiceDegraded("stub breaker open", 0.2)
        if self.mode == "deadline":
            raise DeadlineExceeded("stub deadline passed")
        if self.mode == "bad":
            raise ValueError("undecodable stub payload")
        return SimpleNamespace(consensuses=list(self.records))

    def healthz(self):
        status = "degraded" if self.mode == "degraded" else "ok"
        return {
            "status": status,
            "queue_depth": self.queue_depth,
            "watermark": self.watermark,
            "est_wait_s": 0.25 * max(self.queue_depth, 1),
        }

    def readyz(self):
        return {"ready": self.mode == "ok", "status": self.mode}

    def drain(self, handback=False):
        self.drained.append(handback)
        return []


class _RemoteHarness:
    """One stub remote behind a real ServeHTTPServer with the real
    RpcServerAdapter routes — the wire without the device."""

    def __init__(self):
        self.stub = _StubRemote()
        self.stop_event = threading.Event()
        self.adapter = RpcServerAdapter(
            self.stub, stop_event=self.stop_event
        )
        self.server = ServeHTTPServer(
            MetricsRegistry(),
            health_fn=self.stub.healthz,
            post_routes=self.adapter.post_routes(),
            get_routes={
                "/readyz": lambda: (
                    200, "application/json",
                    json.dumps(self.stub.readyz()).encode(), {},
                ),
            },
        ).start()

    @property
    def address(self):
        return self.server.host, self.server.port

    def client(self, **kw) -> RpcServiceClient:
        host, port = self.address
        kw.setdefault(
            "retry",
            RetryPolicy(max_attempts=4, base_s=0.0, max_s=0.0,
                        classify=wire_transient, sleep=lambda s: None),
        )
        return RpcServiceClient(host, port, **kw).start()

    def close(self):
        self.server.stop()


@pytest.fixture()
def remote():
    h = _RemoteHarness()
    yield h
    h.close()


# ------------------------------------- the shared Replica contract suite


class _InprocStub:
    """The in-process twin of _StubRemote: same surface, no wire."""

    def __init__(self):
        self.mode = "ok"
        self.records = [Sequence("stub1", "ACGTACGT")]
        self.live = True
        self.queue = SimpleNamespace(
            depth=0, high_watermark=64,
            estimated_wait_s=lambda d=None: 0.25,
        )
        self.worker = SimpleNamespace(reap=lambda: None)

    def start(self):
        return self

    def stop(self, drain=True):
        self.live = False

    def kill(self):
        self.live = False

    def healthz(self):
        return {
            "status": "degraded" if self.mode == "degraded" else "ok"
        }

    def drain(self, handback=False):
        return []

    def submit(self, payload, deadline_s=None, **opts):
        from concurrent.futures import Future

        fut: Future = Future()
        if self.mode == "shed":
            fut.set_exception(AdmissionError("stub watermark", 0.2))
        else:
            fut.set_result(
                SimpleNamespace(consensuses=list(self.records))
            )
        return fut


@pytest.fixture(params=["inproc", "rpc"])
def contract_replica(request):
    """One Replica slot over either backend, plus the knobs the
    contract tests poke — the suite itself cannot tell which transport
    it is driving, which is the point."""
    if request.param == "inproc":
        stub = _InprocStub()
        rep = Replica("c0", lambda: stub).start()

        def set_mode(mode):
            stub.mode = mode

        def kill_backend():
            stub.kill()

        yield SimpleNamespace(
            rep=rep, set_mode=set_mode, kill_backend=kill_backend,
            kind="inproc",
        )
        rep.stop(drain=False)
    else:
        harness = _RemoteHarness()
        clients: list = []

        def factory():
            c = harness.client()
            clients.append(c)
            return c

        rep = Replica("c0", factory).start()

        def set_mode(mode):
            harness.stub.mode = mode

        def kill_backend():
            # host loss: the server vanishes AND the handle knows it
            # can no longer make progress — same observable as a dead
            # process (RpcServiceClient.kill on a spawned replica)
            rep.service.kill()
            harness.server.stop()

        yield SimpleNamespace(
            rep=rep, set_mode=set_mode, kill_backend=kill_backend,
            kind="rpc",
        )
        for c in clients:
            c._teardown()
        try:
            harness.close()
        except Exception:  # noqa: BLE001 — already stopped by kill_backend
            pass


def test_transient_probe_errors_demote_instead_of_evicting():
    """A wire flap during a probe (UNAVAILABLE vocabulary) scores the
    replica degraded-ward; a hard failure (refused port) scores toward
    death — the supervisor routes through classify_probe_error so an
    RPC blip cannot evict a replica holding admitted work."""
    stub = _InprocStub()
    rep = Replica("p0", lambda: stub).start()
    flap = RuntimeError("UNAVAILABLE: injected transient flap")
    hard = ConnectionRefusedError("[Errno 111] Connection refused")
    assert rep.classify_probe_error(flap) == rpolicy.PROBE_DEGRADED
    assert rep.classify_probe_error(hard) == rpolicy.PROBE_FAILED
    # degraded-ward run never reaches the death verdict
    for _ in range(10):
        verdict = rep.record_probe_failure(
            repr(flap), outcome=rep.classify_probe_error(flap)
        )
    assert verdict == rpolicy.REPLICA_DEGRADED
    assert rep.state == "degraded"
    # hard failures do
    for _ in range(3):
        verdict = rep.record_probe_failure(
            repr(hard), outcome=rep.classify_probe_error(hard)
        )
    assert verdict == rpolicy.REPLICA_DEAD


def _probe_outcome(rep) -> str:
    """Probe like the supervisor does: an exception IS a failed probe."""
    try:
        return rep.probe()
    except Exception:  # noqa: BLE001 — the supervisor folds this to failed
        return rpolicy.PROBE_FAILED


def test_contract_probe_reflects_remote_health(contract_replica):
    env = contract_replica
    assert _probe_outcome(env.rep) == rpolicy.PROBE_OK
    env.set_mode("degraded")
    assert _probe_outcome(env.rep) == rpolicy.PROBE_DEGRADED
    env.set_mode("ok")
    assert _probe_outcome(env.rep) == rpolicy.PROBE_OK


def test_contract_submit_settles_with_records(contract_replica):
    env = contract_replica
    fut = env.rep.service.submit(b"payload-bytes")
    res = fut.result(timeout=10)
    assert [(r.name, r.sequence) for r in res.consensuses] == [
        ("stub1", "ACGTACGT")
    ]


def test_contract_kill_fails_probes_until_dead_verdict(contract_replica):
    env = contract_replica
    env.kill_backend()
    policy = rpolicy.ProbePolicy(degraded_after=2, dead_after=3)
    verdict = None
    for _ in range(3):
        verdict = policy.observe(_probe_outcome(env.rep))
    assert verdict == rpolicy.REPLICA_DEAD
    assert not env.rep.service.live


def test_contract_state_machine_transitions(contract_replica):
    env = contract_replica
    rep = env.rep
    assert rep.state == "ok" and rep.admitting
    rep.set_state("draining")
    assert not rep.admitting
    rep.set_state("ok")
    assert rep.score(rpolicy.PROBE_FAILED) == "ok"  # one flake: no demotion
    assert rep.score(rpolicy.PROBE_OK) == "ok"


def test_contract_router_integration_shed_fails_over(contract_replica):
    """The shed surface differs in WHERE it appears (sync raise
    in-process, async inner failure over RPC) but the router absorbs
    both: the ticket lands on the healthy replica either way."""
    env = contract_replica
    env.set_mode("shed")
    ok_stub = _InprocStub()
    ok_stub.records = [Sequence("other", "TTTT")]
    ok_rep = Replica("c1", lambda: ok_stub).start()
    router = FleetRouter([env.rep, ok_rep])
    fut = router.submit(b"payload-bytes")
    res = fut.result(timeout=10)
    assert [(r.name, r.sequence) for r in res.consensuses] in (
        [("other", "TTTT")],
        [("stub1", "ACGTACGT")],  # rendezvous may prefer the ok replica
    )
    # and with BOTH replicas shedding, the outer settles with the shed
    ok_stub.mode = "shed"
    with pytest.raises(AdmissionError):
        router.submit(b"payload-bytes").result(timeout=10)


# -------------------------------------------------- transport behavior


def test_rpc_client_maps_remote_errors_to_typed_vocabulary(remote):
    client = remote.client()
    try:
        for mode, exc_type in (
            ("shed", AdmissionError),
            ("degraded", ServiceDegraded),
            ("deadline", DeadlineExceeded),
            ("bad", ValueError),
        ):
            remote.stub.mode = mode
            with pytest.raises(exc_type):
                client.submit(b"x").result(timeout=10)
        # typed Retry-After hints survive the wire
        remote.stub.mode = "shed"
        try:
            client.submit(b"x").result(timeout=10)
        except AdmissionError as e:
            assert e.retry_after_s > 0
    finally:
        client._teardown()


def test_rpc_client_retries_connect_refused_then_fails_over_typed(remote):
    client = remote.client()
    try:
        plan = rfaults.activate(
            FaultPlan.parse("rpc.connect:refused:times=1")
        )
        # the refused dial is resubmitted under the retry policy: the
        # request still lands (probes may or may not have a pooled
        # connection, so push several to guarantee a fresh dial)
        futs = [client.submit(b"dial-me") for _ in range(4)]
        for f in futs:
            res = f.result(timeout=10)
            assert res.consensuses
        assert plan.fired.get(("rpc.connect", "refused"), 0) == 1
        # exhausted budgets surface as the replica-level transport error
        rfaults.activate(FaultPlan.parse("rpc.call:reset:times=99"))
        with pytest.raises(RpcTransportError):
            client.submit(b"resets-forever").result(timeout=10)
    finally:
        client._teardown()


def test_rpc_remote_queue_view_feeds_router_admission(remote):
    remote.stub.queue_depth = 5
    remote.stub.watermark = 8
    client = remote.client()
    try:
        client.healthz()
        assert client.queue.depth == 5
        assert client.queue.high_watermark == 8
        assert client.queue.estimated_wait_s(4) == pytest.approx(1.0)
    finally:
        client._teardown()


def test_rpc_drain_handback_settles_remote_queue_with_shed(remote):
    """The wire encoding of handback(): the remote settles its queued
    requests with the handed-back shed error (503 on the blocked POST),
    which the client surfaces as ServiceDegraded — a REPLICA_FAILURES
    member, so the router re-places the ticket."""
    from kindel_tpu.serve.queue import ServeRequest

    handed_req = ServeRequest(payload=b"q", opts=None)
    remote.stub.drain = lambda handback=False: (
        [handed_req] if handback else []
    )
    client = remote.client()
    try:
        client.drain(handback=True)
        with pytest.raises(ServiceDegraded):
            handed_req.future.result(timeout=0)
    finally:
        client._teardown()


def test_http_front_rejects_oversized_body_with_413_retry_after(remote):
    host, port = remote.address
    remote.server.max_body_bytes = 64
    body = b"A" * 256
    req = urllib.request.Request(
        f"http://{host}:{port}/v1/consensus", data=body, method="POST"
    )
    with pytest.raises(urllib.error.HTTPError) as exc:
        urllib.request.urlopen(req, timeout=30)
    assert exc.value.code == 413
    assert int(exc.value.headers["Retry-After"]) >= 1


def test_max_body_mb_resolves_through_tune(monkeypatch):
    from kindel_tpu import tune

    assert tune.resolve_max_body_mb(7) == (7, "explicit")
    monkeypatch.setenv("KINDEL_TPU_MAX_BODY_MB", "33")
    assert tune.resolve_max_body_mb(None) == (33, "env")
    monkeypatch.setenv("KINDEL_TPU_MAX_BODY_MB", "not-a-number")
    assert tune.resolve_max_body_mb(None) == (
        tune.MAX_BODY_MB_DEFAULT, "default",
    )
    monkeypatch.delenv("KINDEL_TPU_MAX_BODY_MB")
    assert tune.resolve_rpc_timeout_ms(1500.0) == (1500.0, "explicit")
    monkeypatch.setenv("KINDEL_TPU_RPC_TIMEOUT_MS", "2500")
    assert tune.resolve_rpc_timeout_ms(None) == (2500.0, "env")
    monkeypatch.delenv("KINDEL_TPU_RPC_TIMEOUT_MS")
    assert tune.resolve_rpc_timeout_ms(None) == (
        float(tune.RPC_TIMEOUT_MS_DEFAULT), "default",
    )


# ------------------------------------------- idempotency / lost response


def test_idempotency_cache_claims_once_and_coalesces():
    cache = IdempotencyCache(cap=2)
    first, fut = cache.claim("k1")
    assert first
    again, fut2 = cache.claim("k1")
    assert not again and fut2 is fut
    fut.set_result(("resp",))
    # eviction only reaps settled entries
    cache.claim("k2")
    cache.claim("k3")
    assert len(cache) == 2
    first_again, _ = cache.claim("k1")
    assert first_again, "settled k1 should have been evicted"


def test_lost_response_resubmission_dedupes_server_side(remote):
    """Satellite: inject `rpc.call:drop_response` AFTER the server
    applied the request — the resubmission carries the same idempotency
    key, the server answers from the cache (applied exactly once), and
    the outer future settles exactly once with byte-identical FASTA."""
    client = remote.client()
    try:
        before_dedup = default_registry().snapshot().get(
            "kindel_rpc_dedup_hits_total", 0
        )
        plan = rfaults.activate(
            FaultPlan.parse("rpc.call:drop_response:times=1")
        )
        fut = client.submit(b"the-one-request")
        res = fut.result(timeout=10)
        assert plan.fired == {("rpc.call", "drop_response"): 1}
        # the server applied ONCE; the retry was answered from cache
        assert remote.stub.applied == 1
        assert remote.adapter.applied == 1
        after_dedup = default_registry().snapshot().get(
            "kindel_rpc_dedup_hits_total", 0
        )
        assert after_dedup - before_dedup == 1
        # byte-identical to what the server rendered
        assert format_fasta(res.consensuses) == format_fasta(
            remote.stub.records
        )
        # exactly once: the future is settled, and settled correctly
        assert fut.done() and not fut.cancelled()
    finally:
        client._teardown()


def test_garbled_response_resubmits_and_dedupes(remote):
    client = remote.client()
    try:
        plan = rfaults.activate(
            FaultPlan.parse("rpc.call:garbage:times=1")
        )
        res = client.submit(b"garble-me").result(timeout=10)
        assert plan.fired == {("rpc.call", "garbage"): 1}
        assert remote.stub.applied == 1
        assert [r.name for r in res.consensuses] == ["stub1"]
    finally:
        client._teardown()


def test_concurrent_duplicate_keys_apply_once(remote):
    """Racing resubmissions (not just serial retries) coalesce on the
    in-progress future: N simultaneous POSTs with one key → one apply,
    N identical answers."""
    remote.stub.apply_delay_s = 0.1
    host, port = remote.address
    bodies: list = []
    errs: list = []

    def post():
        req = urllib.request.Request(
            f"http://{host}:{port}/v1/consensus", data=b"same",
            method="POST", headers={IDEMPOTENCY_HEADER: "race-key"},
        )
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                bodies.append(resp.read())
        except Exception as e:  # noqa: BLE001 — collected for the assert
            errs.append(repr(e))

    threads = [threading.Thread(target=post) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert remote.stub.applied == 1
    assert len(set(bodies)) == 1


# -------------------------------------------------- trace propagation


def test_trace_id_propagates_over_the_rpc_hop(remote, tmp_path):
    """Satellite: one trace covers caller → wire → remote apply. The
    JSONL span tree is deterministic in SHAPE: rpc.call parents to the
    caller's root, rpc.server carries the SAME trace id and parents to
    rpc.call's span id — across what is, in production, a process
    boundary."""
    out = tmp_path / "spans.jsonl"
    trace.enable_tracing(str(out))
    client = remote.client()
    try:
        with trace.span("test.root") as root:
            res = client.submit(b"traced-request").result(timeout=10)
            assert res.consensuses
            root_trace = root.trace_id
    finally:
        client._teardown()
        trace.disable_tracing()
    spans = [json.loads(line) for line in out.read_text().splitlines()]
    by_name = {}
    for sp in spans:
        by_name.setdefault(sp["name"], []).append(sp)
    (call,) = by_name["rpc.call"]
    (server,) = by_name["rpc.server"]
    (root_sp,) = by_name["test.root"]
    assert call["trace_id"] == root_trace
    assert call["parent_id"] == root_sp["span_id"]
    assert server["trace_id"] == root_trace, "trace id lost on the wire"
    assert server["parent_id"] == call["span_id"]
    assert call["attrs"]["outcome"] == "ok"
    assert server["attrs"]["key"] == call["attrs"]["key"]


def test_trace_covers_wire_to_device_dispatch(tmp_path):
    """End-to-end: a REAL ConsensusService behind the RPC adapter — the
    remote request tree (serve.request → admission/queue/dispatch)
    roots under rpc.server, so one trace id spans router → wire →
    remote worker → device dispatch."""
    from kindel_tpu.serve import ConsensusService
    from tests.test_serve import make_sam

    sam = make_sam(tmp_path / "t.sam", seed=77)
    out = tmp_path / "spans.jsonl"
    stop_event = threading.Event()
    svc = ConsensusService(max_wait_s=0.01, http_port=0)
    adapter = RpcServerAdapter(svc, stop_event=stop_event)
    svc._extra_post_routes.update(adapter.post_routes())
    svc.start()
    trace.enable_tracing(str(out))
    host, port = svc.http_address
    client = RpcServiceClient(host, port).start()
    try:
        with trace.span("test.root") as root:
            res = client.submit(sam.read_bytes()).result(timeout=120)
            assert res.consensuses
            root_trace = root.trace_id
    finally:
        client._teardown()
        trace.disable_tracing()
        svc.stop()
    spans = [json.loads(line) for line in out.read_text().splitlines()]
    named = {}
    for sp in spans:
        named.setdefault(sp["name"], []).append(sp)
    assert all(
        sp["trace_id"] == root_trace
        for name in ("rpc.call", "rpc.server", "serve.request")
        for sp in named[name]
    ), "a stage fell off the trace"
    (server,) = named["rpc.server"]
    request_spans = [
        sp for sp in named["serve.request"]
        if sp["parent_id"] == server["span_id"]
    ]
    assert request_spans, "serve.request did not root under rpc.server"
    # the remote request tree kept its own children (queue wait at least)
    req_ids = {sp["span_id"] for sp in request_spans}
    assert any(
        sp.get("parent_id") in req_ids
        for sp in spans if sp["name"] != "serve.request"
    )


# --------------------------------------------------------- autoscaler


class _ScaleStub(_InprocStub):
    def __init__(self, depth=0, watermark=10):
        super().__init__()
        self.queue = SimpleNamespace(
            depth=depth, high_watermark=watermark,
            estimated_wait_s=lambda d=None: 0.1,
        )


def _scale_fleet(**kw):
    stubs: dict = {}

    def factory(rid, registry):
        stubs[rid] = _ScaleStub()
        return stubs[rid]

    fleet = FleetService(
        replicas=2, service_factory=factory, supervise=False, **kw
    )
    fleet.start()
    return fleet, stubs


def test_autoscaler_scales_up_on_sustained_sheds_only():
    fleet, stubs = _scale_fleet()
    try:
        scaler = FleetAutoscaler(
            fleet, min_replicas=1, max_replicas=4,
            up_after=2, down_after=3, cooldown_evals=2,
        )
        # one shed is a blip, not a trend
        fleet.router.sheds += 1
        assert scaler.evaluate() is None
        assert scaler.evaluate() is None  # no new sheds: run reset
        # sustained sheds: two consecutive pressured evaluations
        fleet.router.sheds += 1
        assert scaler.evaluate() is None
        fleet.router.sheds += 1
        assert scaler.evaluate() == "up"
        assert len(fleet.replicas) == 3
        assert "r2" in [r.replica_id for r in fleet.replicas]
        # the new replica admits and is ranked by the router
        assert any(
            r.replica_id == "r2"
            for r in fleet.router.rank(routing_key(b"x", {}))
        )
    finally:
        fleet.stop(drain=False)


def test_autoscaler_scales_down_lowest_occupancy_via_drain():
    before = default_registry().snapshot()
    fleet, stubs = _scale_fleet()
    try:
        fleet.scale_up()
        assert len(fleet.replicas) == 3
        # r1 is the busy one; r0/r2 idle — lowest occupancy retires
        stubs["r1"].queue.depth = 9
        busy = fleet.replica("r1")
        scaler = FleetAutoscaler(
            fleet, min_replicas=2, max_replicas=4,
            up_after=2, down_after=2, cooldown_evals=0,
        )
        stubs["r1"].queue.depth = 0  # now everyone idle: down pressure
        assert scaler.evaluate() is None
        assert scaler.evaluate() == "down"
        assert len(fleet.replicas) == 2
        assert busy in fleet.replicas, "the busy replica was retired"
        # floor respected forever after
        for _ in range(10):
            scaler.evaluate()
        assert len(fleet.replicas) == 2
    finally:
        fleet.stop(drain=False)
    after = default_registry().snapshot()
    assert _fleet_delta(
        before, after,
        'kindel_fleet_scale_events_total{direction="down"}',
    ) == 1


def test_autoscaler_hysteresis_square_wave_does_not_flap():
    """The pinned no-flapping claim: a square-wave load (alternating
    pressured/idle evaluations) produces NO scale events — consecutive
    runs never accumulate — and even a slow square wave is bounded by
    the cooldown to at most one event per window."""
    fleet, stubs = _scale_fleet()
    try:
        scaler = FleetAutoscaler(
            fleet, min_replicas=1, max_replicas=4,
            up_after=2, down_after=2, cooldown_evals=3,
        )
        events = []
        # fast square wave: period 2 evaluations
        for i in range(20):
            if i % 2 == 0:
                fleet.router.sheds += 1  # pressured edge
            ev = scaler.evaluate()
            if ev:
                events.append(ev)
        assert events == [], f"fast square wave flapped: {events}"
        assert len(fleet.replicas) == 2
        # slow square wave (4 pressured, 4 idle, repeated): tracking a
        # genuinely slow load IS the job, but hysteresis bounds it to
        # at most ONE action per half-period, strictly alternating —
        # never a spawn/retire churn inside one edge
        events = []
        for cycle in range(3):
            for half in range(2):
                half_events = []
                for i in range(4):
                    if half == 0:
                        fleet.router.sheds += 1
                    ev = scaler.evaluate()
                    if ev:
                        half_events.append(ev)
                assert len(half_events) <= 1, (
                    f"multiple actions in one half-period: {half_events}"
                )
                events.extend(half_events)
        assert all(
            a != b for a, b in zip(events, events[1:])
        ), f"same-direction churn: {events}"
        assert 1 <= len(fleet.replicas) <= 4
    finally:
        fleet.stop(drain=False)


def test_fleet_watermark_sheds_feed_the_counter():
    before = default_registry().snapshot()
    fleet, stubs = _scale_fleet(fleet_watermark=1)
    try:
        for s in stubs.values():
            s.queue.depth = 2
        with pytest.raises(AdmissionError):
            fleet.submit(b"over")
        assert fleet.router.sheds >= 1
    finally:
        fleet.stop(drain=False)
    after = default_registry().snapshot()
    assert _fleet_delta(
        before, after, "kindel_fleet_watermark_sheds_total"
    ) >= 1


# ----------------------------------------------------- process replicas


def _names_seqs(records) -> list:
    return [(r.name, r.sequence) for r in records]


@pytest.mark.parametrize("payload_kind", ["bytes", "path"])
def test_process_replica_serves_byte_identical(tmp_path, payload_kind):
    """One spawned replica process, driven through the full contract:
    byte-identical consensus over the wire for both payload kinds."""
    from kindel_tpu.fleet.procreplica import ProcessFleetService
    from kindel_tpu.workloads import bam_to_consensus
    from tests.test_serve import make_sam

    sam = make_sam(tmp_path / "proc.sam", seed=91)
    want = _names_seqs(bam_to_consensus(str(sam)).consensuses)
    payload = sam.read_bytes() if payload_kind == "bytes" else str(sam)
    with ProcessFleetService(
        replicas=1,
        service_config={"max_wait_s": 0.01, "decode_workers": 2},
        probe_interval_s=0.05,
    ) as fleet:
        got = _names_seqs(fleet.request(payload, timeout=120).consensuses)
        assert got == want
        health = fleet.healthz()
        assert health["status"] == "ok"
        # the wire carried the remote health document, aot provenance
        # included (the §15 store is what makes respawns warm)
        (doc,) = [d["healthz"] for d in health["replicas"].values()]
        assert "aot" in doc and "est_wait_s" in doc


def test_process_replica_dedupes_lost_response(tmp_path):
    """The lost-response guarantee ACROSS a real process boundary: the
    response to an applied request is dropped on the wire, the
    resubmission dedupes in the child (applied once — /v1/rpc carries
    the child-side count back), and the caller sees one byte-identical
    settle."""
    from kindel_tpu.fleet.procreplica import ProcessFleetService
    from kindel_tpu.workloads import bam_to_consensus
    from tests.test_serve import make_sam

    sam = make_sam(tmp_path / "dedup.sam", seed=23)
    want = _names_seqs(bam_to_consensus(str(sam)).consensuses)
    with ProcessFleetService(
        replicas=1,
        service_config={"max_wait_s": 0.01, "decode_workers": 2},
        probe_interval_s=0.05,
    ) as fleet:
        baseline = fleet.rpc_stats()
        plan = rfaults.activate(
            FaultPlan.parse("rpc.call:drop_response:times=1")
        )
        fut = fleet.submit(sam.read_bytes())
        res = fut.result(timeout=120)
        rfaults.deactivate()
        assert plan.fired == {("rpc.call", "drop_response"): 1}
        assert _names_seqs(res.consensuses) == want
        stats = fleet.rpc_stats()
        # one request, one apply, one cache-served resubmission
        assert stats["applied"] - baseline["applied"] == 1
        assert stats["dedup_hits"] - baseline["dedup_hits"] == 1


# ---------------------------------------------------------- the flagship


def test_flagship_proc_fleet_chaos_sigkill_and_autoscale_drain():
    """The flagship: 3 replica PROCESSES under injected network faults
    (dropped responses, slow wire, garbage, a refused dial), one
    replica SIGKILLed and another autoscale-drained mid-load. Every
    admitted future settles exactly once, the FASTA sha256 equals a
    single-replica in-process run, the killed slot is respawned as a
    fresh process, and the fault ledger records exactly the injected
    plan."""
    from benchmarks.serve_load import run_load

    # single-replica in-process reference: the byte-identity anchor
    reference = run_load(clients=2, requests_per_client=3)
    assert reference["errors"] == 0
    assert reference["fasta_distinct"] == 1

    plan = rfaults.activate(FaultPlan.parse(
        "seed=11,"
        "rpc.call:drop_response:times=2:after=1,"
        "rpc.call:slow:times=2:delay=0.02,"
        "rpc.call:garbage:times=1:after=4,"
        "rpc.connect:refused:times=1"
    ))
    before = default_registry().snapshot()
    killed: dict = {}

    def chaos(svc):
        time.sleep(0.2)
        victim = svc.replica("r1")
        killed["gen"] = victim.generation
        svc.kill_replica("r1")
        time.sleep(0.4)
        # the autoscaler's scale-down path, forced deterministically:
        # drain the lowest-occupancy replica and retire it
        svc.scale_down()
        killed["victim"] = victim
        # hold the report until the killed slot's respawn completes —
        # chaos is a joined load thread, so the final fleet state in
        # the report is the steady state, not a mid-respawn snapshot
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            roster_states = {r.state for r in svc.roster()}
            if roster_states == {"ok"}:
                return
            time.sleep(0.05)
        raise AssertionError(
            f"fleet never converged after chaos: "
            f"{[(r.replica_id, r.state) for r in svc.roster()]}"
        )

    report = run_load(
        clients=3, requests_per_client=3, procs=3,
        probe_interval_s=0.02, chaos=chaos,
    )
    after = default_registry().snapshot()

    # exactly once: every admitted request resolved, none errored,
    # none duplicated
    assert "chaos_errors" not in report, report.get("chaos_errors")
    assert report["errors"] == 0
    assert report["completed"] == report["requests"] == 9
    # byte-identical to the in-process single-replica reference,
    # across the wire, under faults, through a kill and a retire
    assert report["fasta_distinct"] == 1
    assert report["fasta_sha256"] == reference["fasta_sha256"]
    # the injected network plan fired exactly as written (the refused
    # dial is opportunistic — it needs a fresh connect after
    # activation — but every response-path fault is deterministic)
    assert plan.fired[("rpc.call", "drop_response")] == 2
    assert plan.fired[("rpc.call", "slow")] == 2
    assert plan.fired[("rpc.call", "garbage")] == 1
    # dropped/garbled responses were resubmitted (the client-side retry
    # counter lives in THIS process, so it is deterministic); whether a
    # given resubmission hit the dedupe cache or failed over depends on
    # which replica the chaos killed — the process-level dedupe
    # guarantee is pinned deterministically in
    # test_process_replica_dedupes_lost_response
    assert report["rpc"]["retries"] >= 3
    # the SIGKILL was detected and the process respawned
    assert _fleet_delta(before, after, "kindel_fleet_evictions_total") >= 1
    assert _fleet_delta(before, after, "kindel_fleet_respawns_total") >= 1
    assert report["rpc"]["scale_events"]["down"] == 1
    # the fleet ended at 2 live replicas (3 - retired), all ok, and the
    # killed slot came back as a NEW process generation
    assert killed["victim"].generation == killed["gen"] + 1
    states = set(report["fleet"]["replicas"].values())
    assert states == {"ok"}, report["fleet"]["replicas"]
    assert len(report["fleet"]["replicas"]) == 2
