"""Chaos suite for kindel_tpu.resilience: seeded fault plans injected
into the real hot paths, asserting the invariants DESIGN.md §13 states:

  * every admitted request completes — success or typed error — no
    matter what faults the device path throws (OOM, stalls, a killed
    worker thread);
  * /healthz transitions ok → degraded → ok as the circuit breaker
    trips and recovers, shedding new work with ServiceDegraded while
    open;
  * retry / degrade / breaker metrics match the injected fault counts
    deterministically (the plan records what it fired);
  * the disabled-path fault hooks are allocation-free (tracemalloc pin,
    the same bar as the obs no-op spans);
  * truncated/corrupt input dies with a typed TruncatedInputError
    naming the offset / chunk, and the streamed decoder reports which
    chunk died.

Everything runs on the CPU backend with injected no-sleep retry
policies, so the suite is deterministic and fast enough for tier-1.
"""

import threading
import time
import tracemalloc
from pathlib import Path

import pytest

from kindel_tpu.batch import BatchOptions
from kindel_tpu.io.errors import TruncatedInputError
from kindel_tpu.resilience import breaker as rbreaker
from kindel_tpu.resilience import faults as rfaults
from kindel_tpu.resilience import policy as rpolicy
from kindel_tpu.resilience.breaker import CircuitBreaker, FlushTimeout
from kindel_tpu.resilience.faults import (
    FaultPlan,
    FaultSpec,
    InjectedFault,
    InjectedWorkerKill,
)
from kindel_tpu.resilience.policy import RetryPolicy
from kindel_tpu.obs.metrics import default_registry
from kindel_tpu.serve import (
    AdmissionError,
    ConsensusClient,
    ConsensusService,
    RequestQueue,
    ServeRequest,
    ServiceDegraded,
)
from kindel_tpu.workloads import bam_to_consensus

from tests.test_serve import make_sam

_NOSLEEP = dict(sleep=lambda s: None)


@pytest.fixture(autouse=True)
def _clean_fault_state():
    """No fault plan or pinned retry policy may leak between tests (or
    into the rest of the suite — the hooks are process-global)."""
    rfaults.deactivate()
    prev = rpolicy.set_default_policy(None)
    yield
    rfaults.deactivate()
    rpolicy.set_default_policy(prev)


def _names_seqs(records) -> list:
    return [(r.name, r.sequence) for r in records]


def _counter_delta(before: dict, after: dict, prefix: str) -> int:
    """Sum a (possibly labeled) counter family across both snapshots."""

    def total(snap):
        return sum(
            int(v) for k, v in snap.items()
            if k == prefix or k.startswith(prefix + "{")
        )

    return total(after) - total(before)


def _labeled(snap: dict, name: str, **labels) -> int:
    """One labeled child's value, tolerant of label render order."""
    for k, v in snap.items():
        if not k.startswith(name + "{"):
            continue
        if all(f'{lk}="{lv}"' in k for lk, lv in labels.items()):
            return int(v)
    return 0


# ------------------------------------------------------------ fault plans


def test_fault_plan_parse_grammar():
    plan = FaultPlan.parse(
        "seed=7, device.dispatch:oom:2; serve.flush:stall:delay=0.25,"
        "io.read_chunk:truncate:after=1, serve.worker:kill:p=0.5"
    )
    assert plan.seed == 7
    by_site = {s.site: s for s in plan.specs}
    assert by_site["device.dispatch"].kind == "oom"
    assert by_site["device.dispatch"].times == 2
    assert by_site["serve.flush"].delay_s == 0.25
    assert by_site["io.read_chunk"].after == 1
    assert by_site["serve.worker"].p == 0.5


@pytest.mark.parametrize("bad", [
    "device.dispatch",             # no kind
    "device.dispatch:explode",     # unknown kind
    "nowhere.nohook:oom",          # unknown site
    "device.dispatch:oom:wat=1",   # unknown option
])
def test_fault_plan_parse_rejects(bad):
    with pytest.raises(ValueError):
        FaultPlan.parse(bad)


def test_fault_plan_times_and_after_fire_counts():
    plan = rfaults.activate(
        FaultPlan.parse("device.dispatch:oom:times=2:after=1")
    )
    rfaults.hook("device.dispatch")  # hit 1: skipped (after=1)
    for _ in range(2):               # hits 2-3: fire
        with pytest.raises(InjectedFault):
            rfaults.hook("device.dispatch")
    rfaults.hook("device.dispatch")  # hit 4: exhausted (times=2)
    assert plan.fired == {("device.dispatch", "oom"): 2}
    assert plan.hits("device.dispatch") == 4


def test_seeded_probability_replays_identically():
    """Same seed + same hit order → the same fault sequence (the whole
    point of a *deterministic* chaos harness)."""

    def run(seed):
        plan = FaultPlan.parse(f"seed={seed},serve.flush:error:times=100:p=0.4")
        outcomes = []
        for _ in range(50):
            try:
                plan.fire("serve.flush")
                outcomes.append(0)
            except InjectedFault:
                outcomes.append(1)
        return outcomes

    a, b = run(3), run(3)
    assert a == b
    assert 0 < sum(a) < 50  # p actually gates: some fired, some did not
    assert run(4) != a      # and the seed actually matters


def test_stall_fault_sleeps_without_raising():
    slept = []
    plan = FaultPlan(
        [FaultSpec("serve.flush", "stall", delay_s=0.2)],
        sleep=slept.append,
    )
    rfaults.activate(plan)
    rfaults.hook("serve.flush")  # must not raise
    assert slept == [0.2]


def test_truncate_fault_halves_chunk_and_kill_is_typed():
    rfaults.activate(FaultPlan.parse("io.read_chunk:truncate"))
    assert rfaults.hook_bytes("io.read_chunk", b"x" * 64) == b"x" * 32
    assert rfaults.hook_bytes("io.read_chunk", b"y" * 64) == b"y" * 64
    rfaults.activate(FaultPlan.parse("serve.worker:kill"))
    with pytest.raises(InjectedWorkerKill):
        rfaults.hook("serve.worker")


def test_disabled_hooks_are_allocation_free():
    """The acceptance pin: with no plan active, hook()/hook_bytes() on a
    hot path allocate nothing (same bar as the obs no-op span)."""
    rfaults.deactivate()
    payload = b"chunk"

    def burst(n):
        for _ in range(n):
            rfaults.hook("device.dispatch")
            rfaults.hook_bytes("io.read_chunk", payload)

    burst(64)  # warm any lazy interning
    tracemalloc.start()
    try:
        before = tracemalloc.take_snapshot()
        burst(4096)
        after = tracemalloc.take_snapshot()
    finally:
        tracemalloc.stop()
    faults_py = str(Path(rfaults.__file__))
    leaked = sum(
        stat.size_diff
        for stat in after.compare_to(before, "filename")
        if stat.traceback[0].filename == faults_py and stat.size_diff > 0
    )
    # a few dozen bytes of tracemalloc frame bookkeeping is constant;
    # the pin is that nothing scales with the 4096-call burst
    assert leaked < 512, (
        f"disabled fault hooks allocated {leaked} bytes over 4096 calls"
    )


# ------------------------------------------------------- classification


def test_transient_and_oom_classification():
    oom = RuntimeError(
        "RESOURCE_EXHAUSTED: Attempting to allocate 1.21G. That was not "
        "possible."
    )
    flap = ConnectionError("UNAVAILABLE: Socket closed")
    corrupt = ValueError("corrupt BAM record at byte 12")
    assert rpolicy.is_transient(oom) and rpolicy.is_oom(oom)
    assert rpolicy.is_transient(flap) and not rpolicy.is_oom(flap)
    assert not rpolicy.is_transient(corrupt)
    assert rpolicy.classify(oom) == "transient"
    assert rpolicy.classify(corrupt) == "fatal"
    # injected faults carry the production markers…
    inj = InjectedFault("serve.flush", "oom", "RESOURCE_EXHAUSTED: injected")
    assert rpolicy.is_transient(inj) and rpolicy.is_oom(inj)
    # …except a worker kill, which must never be retried
    kill = InjectedWorkerKill("serve.worker", "kill", "UNAVAILABLE: kill")
    assert not rpolicy.is_transient(kill)


# --------------------------------------------------------- retry policy


def test_retry_recovers_and_counts_outcomes():
    before = default_registry().snapshot()
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise RuntimeError("UNAVAILABLE: injected flap")
        return "ok"

    policy = RetryPolicy(max_attempts=3, **_NOSLEEP)
    assert policy.run("pipeline.slab", flaky) == "ok"
    after = default_registry().snapshot()
    assert _labeled(after, "kindel_retry_total",
                    site="pipeline.slab", outcome="retried") - _labeled(
        before, "kindel_retry_total",
        site="pipeline.slab", outcome="retried") == 2
    assert _labeled(after, "kindel_retry_total",
                    site="pipeline.slab", outcome="recovered") - _labeled(
        before, "kindel_retry_total",
        site="pipeline.slab", outcome="recovered") == 1


def test_retry_fatal_propagates_immediately():
    calls = []

    def broken():
        calls.append(1)
        raise ValueError("corrupt input — not the device's fault")

    with pytest.raises(ValueError):
        RetryPolicy(max_attempts=5, **_NOSLEEP).run("batch.cohort", broken)
    assert len(calls) == 1, "non-transient error must not be retried"


def test_retry_exhausts_after_max_attempts():
    calls = []

    def always_flaky():
        calls.append(1)
        raise RuntimeError("DEADLINE_EXCEEDED: injected")

    with pytest.raises(RuntimeError):
        RetryPolicy(max_attempts=3, **_NOSLEEP).run(
            "batch.cohort", always_flaky
        )
    assert len(calls) == 3


def test_backoff_is_jittered_exponential_and_capped():
    import random

    policy = RetryPolicy(base_s=0.1, max_s=1.0, rng=random.Random(0))
    for attempt in (1, 2, 3, 8):
        cap = min(1.0, 0.1 * 2 ** attempt)
        draws = {policy.backoff_s(attempt) for _ in range(50)}
        assert all(0 <= d <= cap for d in draws)
        assert len(draws) > 1, "no jitter"


# ------------------------------------------------------- circuit breaker


def _fake_clock(start=1000.0):
    t = [start]

    def clock():
        return t[0]

    clock.advance = lambda dt: t.__setitem__(0, t[0] + dt)
    return clock


def test_breaker_state_machine_and_gauge():
    from kindel_tpu.obs.metrics import MetricsRegistry

    clock = _fake_clock()
    reg = MetricsRegistry()
    before = default_registry().snapshot()
    br = CircuitBreaker(
        failure_threshold=3, reset_s=5.0, clock=clock, metrics=reg
    )
    assert br.state == rbreaker.CLOSED
    br.record_failure()
    br.record_failure()
    assert br.state == rbreaker.CLOSED  # under threshold
    br.record_failure()
    assert br.state == rbreaker.OPEN
    assert reg.snapshot()["kindel_breaker_state"] == 2
    assert not br.allow_admission()
    assert 0 < br.retry_after_s() <= 5.0
    clock.advance(5.1)
    assert br.state == rbreaker.HALF_OPEN
    assert reg.snapshot()["kindel_breaker_state"] == 1
    # exactly ONE probe is admitted while half-open
    assert br.allow_admission()
    assert not br.allow_admission()
    br.record_success()
    assert br.state == rbreaker.CLOSED
    assert reg.snapshot()["kindel_breaker_state"] == 0
    after = default_registry().snapshot()
    assert _counter_delta(before, after, "kindel_breaker_trips_total") == 1


def test_breaker_failed_probe_reopens():
    clock = _fake_clock()
    br = CircuitBreaker(failure_threshold=1, reset_s=2.0, clock=clock)
    br.record_failure()
    assert br.state == rbreaker.OPEN
    clock.advance(2.1)
    assert br.allow_admission()  # the half-open probe
    br.record_failure()          # probe failed
    assert br.state == rbreaker.OPEN
    clock.advance(2.1)
    assert br.state == rbreaker.HALF_OPEN  # re-armed reset timer


# ---------------------------------------------- queue under concurrency


def test_queue_concurrent_load_every_admitted_future_resolves_once():
    """The satellite invariant: under concurrent producers + consumers
    with tight deadlines and a low watermark, every ADMITTED request's
    future resolves exactly once (served, expired, or failed at close),
    and every rejection is a typed AdmissionError."""
    q = RequestQueue(max_depth=64, high_watermark=8)
    opts = BatchOptions()
    resolutions: dict[int, int] = {}
    res_lock = threading.Lock()
    admitted: list[ServeRequest] = []
    admitted_lock = threading.Lock()
    rejects = []
    n_producers, per_producer = 6, 30
    stop = threading.Event()

    def track(req, key):
        def done(_fut):
            with res_lock:
                resolutions[key] = resolutions.get(key, 0) + 1

        req.future.add_done_callback(done)

    def produce(pid):
        for i in range(per_producer):
            req = ServeRequest(
                payload=f"p{pid}-{i}", opts=opts,
                # every third request gets a deadline tight enough that
                # some expire while queued
                deadline=(
                    time.monotonic() + 0.002 if i % 3 == 0 else None
                ),
            )
            key = pid * 1000 + i
            track(req, key)
            try:
                q.submit(req)
            except AdmissionError as e:
                rejects.append(e)
                continue
            with admitted_lock:
                admitted.append((key, req))

    def consume():
        while not stop.is_set():
            req = q.get(timeout=0.01)
            if req is None:
                continue
            # simulate service: settle exactly once, rarely slowly
            if req.future.set_running_or_notify_cancel():
                req.future.set_result("served")
            time.sleep(0.001)

    consumers = [threading.Thread(target=consume) for _ in range(2)]
    for t in consumers:
        t.start()
    producers = [
        threading.Thread(target=produce, args=(pid,))
        for pid in range(n_producers)
    ]
    for t in producers:
        t.start()
    for t in producers:
        t.join()
    # drain, then stop the consumers
    deadline = time.monotonic() + 10
    while q.depth and time.monotonic() < deadline:
        time.sleep(0.01)
    stop.set()
    for t in consumers:
        t.join()
    for req in q.close():
        if req.future.set_running_or_notify_cancel():
            req.future.set_exception(RuntimeError("closed"))

    total = n_producers * per_producer
    assert len(admitted) + len(rejects) == total
    assert all(isinstance(e, AdmissionError) for e in rejects)
    # watermark 8 against 6 producers racing 2 consumers: some rejects
    # must actually have happened for this test to mean anything
    assert rejects, "no admission rejects — watermark never engaged"
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        with res_lock:
            if all(key in resolutions for key, _ in admitted):
                break
        time.sleep(0.01)
    with res_lock:
        unresolved = [k for k, _ in admitted if k not in resolutions]
        multi = {k: n for k, n in resolutions.items() if n != 1}
    assert not unresolved, f"{len(unresolved)} admitted futures never resolved"
    assert not multi, f"futures resolved more than once: {multi}"


# ------------------------------------------------------ typed I/O errors


def test_truncated_bam_bytes_raise_typed_error_with_offset():
    from kindel_tpu.io.bam import parse_bam_bytes

    # minimal BAM: magic, no header text, one ref "r" of length 100,
    # then a record that claims 200 body bytes but provides 10
    import struct

    head = (
        b"BAM\x01" + struct.pack("<i", 0) + struct.pack("<i", 1)
        + struct.pack("<i", 2) + b"r\x00" + struct.pack("<i", 100)
    )
    data = head + struct.pack("<i", 200) + b"\x00" * 10
    with pytest.raises(TruncatedInputError) as exc:
        parse_bam_bytes(data)
    assert exc.value.offset == len(head)
    assert "block_size=200" in str(exc.value)
    assert f"offset={len(head)}" in str(exc.value)


def test_truncated_bgzf_member_raises_typed_error():
    import gzip

    from kindel_tpu.io import bgzf

    whole = gzip.compress(b"payload" * 64)
    with pytest.raises(TruncatedInputError):
        bgzf.decompress(whole[: len(whole) // 2])


def test_streamed_decode_names_the_dead_chunk(tmp_path):
    """A BAM whose final record is cut off mid-body dies with a typed
    error carrying the path and the 0-based chunk index."""
    import gzip

    from kindel_tpu.io.stream import stream_alignment

    sam = make_sam(tmp_path / "t.sam", seed=1)
    # build an uncompressed-BAM-equivalent via the battle-tested writer
    # in bench.py? No — simplest: gzip a truncated *BAM-shaped* stream
    import struct

    head = (
        b"BAM\x01" + struct.pack("<i", 0) + struct.pack("<i", 1)
        + struct.pack("<i", 2) + b"r\x00" + struct.pack("<i", 100)
    )
    body = head + struct.pack("<i", 500) + b"\x00" * 40  # truncated record
    path = tmp_path / "trunc.bam"
    path.write_bytes(gzip.compress(body))
    with pytest.raises(TruncatedInputError) as exc:
        for _ in stream_alignment(str(path)):
            pass
    assert str(exc.value.path) == str(path)
    assert exc.value.chunk_index is not None
    assert f"file={path}" in str(exc.value)


def test_io_read_chunk_truncate_fault_streams_typed_error(tmp_path):
    """The chaos-injection route: a healthy file + an io.read_chunk
    truncate fault reproduces the truncated-stream failure end to end,
    and the streamed reducer records the casualty."""
    import gzip
    import struct

    from kindel_tpu.io.stream import stream_alignment

    # a healthy single-record BAM (record body 40 bytes, block_size 40)
    head = (
        b"BAM\x01" + struct.pack("<i", 0) + struct.pack("<i", 1)
        + struct.pack("<i", 2) + b"r\x00" + struct.pack("<i", 100)
    )
    rec = struct.pack("<i", 40) + b"\x00" * 40
    path = tmp_path / "ok.bam"
    path.write_bytes(gzip.compress(head + rec))
    # sanity: streams clean without the fault
    assert sum(1 for _ in stream_alignment(str(path))) >= 0
    plan = rfaults.activate(FaultPlan.parse("io.read_chunk:truncate"))
    with pytest.raises(TruncatedInputError) as exc:
        for _ in stream_alignment(str(path)):
            pass
    assert plan.fired == {("io.read_chunk", "truncate"): 1}
    assert str(exc.value.path) == str(path)


# ------------------------------------------------- offline dispatch sites


def _mini_events(tmp_path, seed=21):
    from kindel_tpu.events import extract_events
    from kindel_tpu.io import load_alignment

    sam = make_sam(tmp_path / f"ev{seed}.sam", seed=seed)
    return extract_events(load_alignment(str(sam)))


def test_pipeline_slab_oom_halves_and_recovers(tmp_path):
    """Device OOM surviving the retries halves the slab size (doubles
    the count) and re-runs — output identical to the clean run."""
    jax = pytest.importorskip("jax")
    del jax
    from kindel_tpu.pipeline import pipelined_consensus

    ev = _mini_events(tmp_path)
    rid = ev.present_ref_ids[0]
    want, wmin, wmax = pipelined_consensus(ev, rid, 2)

    rpolicy.set_default_policy(RetryPolicy(max_attempts=2, **_NOSLEEP))
    before = default_registry().snapshot()
    # 2 slabs × 2 attempts = 2 dispatch-hook hits per impl run; times=2
    # exhausts the first run's retry budget exactly, the halved re-run
    # (4 slabs) sees no faults
    plan = rfaults.activate(FaultPlan.parse("device.dispatch:oom:times=2"))
    got, gmin, gmax = pipelined_consensus(ev, rid, 2)
    after = default_registry().snapshot()
    assert plan.fired == {("device.dispatch", "oom"): 2}
    assert (got.sequence, gmin, gmax) == (want.sequence, wmin, wmax)
    assert _labeled(after, "kindel_degrade_total",
                    site="pipeline.slab", action="halve_slab") - _labeled(
        before, "kindel_degrade_total",
        site="pipeline.slab", action="halve_slab") == 1


def test_batch_cohort_transient_launch_retries(tmp_path):
    """A transient device error at cohort launch costs a retry, not the
    cohort."""
    pytest.importorskip("jax")
    from concurrent.futures import ThreadPoolExecutor

    from kindel_tpu.batch import _call_and_assemble
    from kindel_tpu.serve.worker import decode_request

    sam = make_sam(tmp_path / "cohort.sam", seed=31)
    opts = BatchOptions()
    units = decode_request(ServeRequest(payload=str(sam), opts=opts))
    with ThreadPoolExecutor(2) as pool:
        want = _call_and_assemble(list(units), opts, pool, [str(sam)])

    rpolicy.set_default_policy(RetryPolicy(max_attempts=2, **_NOSLEEP))
    before = default_registry().snapshot()
    plan = rfaults.activate(FaultPlan.parse("device.dispatch:error:1"))
    units2 = decode_request(ServeRequest(payload=str(sam), opts=opts))
    with ThreadPoolExecutor(2) as pool:
        got = _call_and_assemble(list(units2), opts, pool, [str(sam)])
    after = default_registry().snapshot()
    assert plan.fired == {("device.dispatch", "error"): 1}
    assert [g[0] for g in got] == [w[0] for w in want]
    assert _labeled(after, "kindel_retry_total",
                    site="batch.cohort", outcome="recovered") - _labeled(
        before, "kindel_retry_total",
        site="batch.cohort", outcome="recovered") == 1


def test_batch_cohort_assembly_oom_bisects(tmp_path, monkeypatch):
    """An OOM surfacing at download/assembly (where a real async XLA OOM
    materializes) bisects the group and re-dispatches the halves."""
    pytest.importorskip("jax")
    from concurrent.futures import ThreadPoolExecutor

    import kindel_tpu.batch as batch_mod
    from kindel_tpu.serve.worker import decode_request

    opts = BatchOptions()
    units = []
    paths = []
    for i in range(2):
        sam = make_sam(tmp_path / f"b{i}.sam", ref=f"bref{i}", seed=40 + i)
        us = decode_request(ServeRequest(payload=str(sam), opts=opts))
        for u in us:
            u.sample_idx = i
        units.extend(us)
        paths.append(str(sam))
    with ThreadPoolExecutor(2) as pool:
        want = batch_mod._call_and_assemble(list(units), opts, pool, paths)

    real_assemble = batch_mod._assemble_outputs
    state = {"failed": False}

    def flaky_assemble(us, out, o, pool, ps):
        if not state["failed"] and len(us) > 1:
            state["failed"] = True
            raise RuntimeError(
                "RESOURCE_EXHAUSTED: out of memory while downloading"
            )
        return real_assemble(us, out, o, pool, ps)

    monkeypatch.setattr(batch_mod, "_assemble_outputs", flaky_assemble)
    before = default_registry().snapshot()
    with ThreadPoolExecutor(2) as pool:
        got = batch_mod._call_and_assemble(list(units), opts, pool, paths)
    after = default_registry().snapshot()
    assert state["failed"], "the synthetic OOM never fired"
    assert [g[0] for g in got] == [w[0] for w in want]
    assert _labeled(after, "kindel_degrade_total",
                    site="batch.cohort", action="bisect") - _labeled(
        before, "kindel_degrade_total",
        site="batch.cohort", action="bisect") == 1


# ------------------------------------------------------ serve chaos path


def test_serve_flush_oom_breaker_sheds_and_recovers(tmp_path):
    """The flagship chaos scenario: injected device OOMs on the serve
    flush path. Every submitted request completes correctly (via the
    numpy fallback while the device 'fails'), /healthz walks
    ok → degraded → ok, new work sheds with ServiceDegraded while open,
    and the retry/degrade/breaker metrics match the plan exactly."""
    sam = make_sam(tmp_path / "chaos.sam", seed=77)
    want = [
        (r.name, r.sequence)
        for r in bam_to_consensus(str(sam)).consensuses
    ]
    before = default_registry().snapshot()
    plan = rfaults.activate(FaultPlan.parse("serve.flush:oom:times=5"))
    with ConsensusService(
        max_wait_s=0.01,
        retry=RetryPolicy(max_attempts=2, **_NOSLEEP),
        breaker_threshold=1,
        breaker_reset_s=0.2,
    ) as svc:
        client = ConsensusClient(svc)
        assert svc.healthz()["status"] == "ok"

        # request 1: both attempts OOM (fires 1-2) → retry exhausted →
        # breaker trips open → numpy fallback still serves it correctly
        assert _names_seqs(client.consensus(str(sam), timeout=120)) == want
        assert svc.healthz()["status"] == "degraded"
        assert svc.breaker.state == rbreaker.OPEN

        # while open, new submissions shed with a 503-shaped typed error
        with pytest.raises(ServiceDegraded) as shed:
            svc.submit(str(sam))
        assert shed.value.retry_after_s > 0

        # request 2: the half-open probe; both attempts OOM (fires 3-4)
        # → breaker re-opens — but the request itself is still served
        time.sleep(0.25)
        assert svc.healthz()["status"] == "degraded"  # half-open ≠ ok
        assert _names_seqs(client.consensus(str(sam), timeout=120)) == want
        assert svc.breaker.state == rbreaker.OPEN

        # request 3: probe again; attempt 1 OOMs (fire 5), attempt 2
        # succeeds on the real device path → breaker closes
        time.sleep(0.25)
        assert _names_seqs(client.consensus(str(sam), timeout=120)) == want
        assert svc.breaker.state == rbreaker.CLOSED
        assert svc.healthz()["status"] == "ok"
        svc_snap = svc.metrics.snapshot()
    after = default_registry().snapshot()

    # the injected-fault ledger is exact
    assert plan.fired == {("serve.flush", "oom"): 5}
    # breaker: closed→open twice (initial trip + failed probe)
    assert _counter_delta(before, after, "kindel_breaker_trips_total") == 2
    assert svc_snap["kindel_breaker_state"] == 0
    assert svc_snap["kindel_serve_degraded_rejects_total"] == 1
    # retry ledger: 3 retried (one per request), 2 exhausted, 1 recovered
    for outcome, n in (("retried", 3), ("exhausted", 2), ("recovered", 1)):
        assert _labeled(after, "kindel_retry_total",
                        site="serve.flush", outcome=outcome) - _labeled(
            before, "kindel_retry_total",
            site="serve.flush", outcome=outcome) == n, outcome
    # degrade ledger: two numpy fallbacks, both counted on both registries
    assert _labeled(after, "kindel_degrade_total",
                    site="serve.flush", action="numpy_fallback") - _labeled(
        before, "kindel_degrade_total",
        site="serve.flush", action="numpy_fallback") == 2
    assert _counter_delta(
        before, after, "kindel_fallback_numpy_total") == 2
    assert svc_snap["kindel_serve_numpy_fallback_total"] == 2


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
def test_serve_worker_kill_restarts_and_serves(tmp_path):
    """A fault-killed worker loop is auto-restarted by the supervisor;
    requests submitted after the kill are still served correctly."""
    sam = make_sam(tmp_path / "kill.sam", seed=55)
    want = [
        (r.name, r.sequence)
        for r in bam_to_consensus(str(sam)).consensuses
    ]
    plan = rfaults.activate(FaultPlan.parse("serve.worker:kill"))
    with ConsensusService(max_wait_s=0.01) as svc:
        # one of the two loops dies on its first hook hit; the
        # supervisor (100 ms cadence) must resurrect it
        deadline = time.monotonic() + 10
        while plan.fired_total() == 0 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert plan.fired == {("serve.worker", "kill"): 1}
        got = ConsensusClient(svc).consensus(str(sam), timeout=120)
        snap = svc.metrics.snapshot()
    assert _names_seqs(got) == want
    restarts = sum(
        int(v) for k, v in snap.items()
        if k.startswith("kindel_serve_worker_restarts_total{")
    )
    assert restarts >= 1, snap


def test_serve_watchdog_fails_only_the_hung_flush(tmp_path):
    """A stalled flush is timed out by the watchdog: its requests fail
    with the typed FlushTimeout, the stalled thread's late completion
    loses the settle race quietly, and the NEXT request serves fine."""
    sam = make_sam(tmp_path / "hang.sam", seed=66)
    want = [
        (r.name, r.sequence)
        for r in bam_to_consensus(str(sam)).consensuses
    ]
    plan = rfaults.activate(
        FaultPlan.parse("serve.flush:stall:delay=0.8")
    )
    with ConsensusService(
        max_wait_s=0.01,
        watchdog_s=0.15,
        breaker_threshold=100,  # keep the breaker out of this scenario
    ) as svc:
        fut = svc.submit(str(sam))
        with pytest.raises(FlushTimeout):
            fut.result(timeout=30)
        snap1 = svc.metrics.snapshot()
        assert snap1["kindel_serve_flush_watchdog_total"] == 1
        # wait out the stall so the late flush resolves its lost race
        time.sleep(0.9)
        got = ConsensusClient(svc).consensus(str(sam), timeout=120)
        snap2 = svc.metrics.snapshot()
    assert plan.fired == {("serve.flush", "stall"): 1}
    assert _names_seqs(got) == want
    # the watchdog-failed request counted exactly once as an error
    assert snap2["kindel_serve_requests_failed_total"] == 1
    assert snap2["kindel_serve_requests_total"] == 2


def test_serve_decode_interrupt_resolves_future_and_reraises(
    tmp_path, monkeypatch
):
    """The satellite bugfix: KeyboardInterrupt/SystemExit inside the
    per-request isolation boundary must resolve the future as a
    *shutdown*, not masquerade as that request's decode failure."""
    import kindel_tpu.serve.worker as worker_mod

    def interrupted(req, **kw):
        raise KeyboardInterrupt

    monkeypatch.setattr(worker_mod, "decode_request", interrupted)
    sam = make_sam(tmp_path / "ki.sam", seed=3)
    with ConsensusService(max_wait_s=0.01) as svc:
        fut = svc.submit(str(sam))
        with pytest.raises(RuntimeError, match="interrupted"):
            fut.result(timeout=30)


def test_warmup_compile_fault_is_best_effort(tmp_path):
    """A fault at the device.compile hook (AOT warmup) must not take
    the service down: /healthz surfaces the error, requests still
    serve, paying their own compile — warmup is best-effort by design."""
    sam = make_sam(tmp_path / "wc.sam", seed=8)
    want = [
        (r.name, r.sequence)
        for r in bam_to_consensus(str(sam)).consensuses
    ]
    plan = rfaults.activate(FaultPlan.parse("device.compile:error"))
    with ConsensusService(max_wait_s=0.01, warmup=True) as svc:
        assert svc.wait_warm(timeout=60)
        health = svc.healthz()
        assert health["status"] == "ok"
        assert "UNAVAILABLE" in health.get("warmup_error", "")
        got = ConsensusClient(svc).consensus(str(sam), timeout=120)
    assert plan.fired == {("device.compile", "error"): 1}
    assert _names_seqs(got) == want


# ----------------------------------------------------------- CLI surface


def test_cli_faults_flag_activates_plan(capsys):
    from kindel_tpu.cli import main

    assert main(["--faults", "seed=9,serve.flush:oom:2", "version"]) == 0
    plan = rfaults.active_plan()
    assert plan is not None and plan.seed == 9
    assert plan.specs[0].site == "serve.flush"
    assert plan.specs[0].times == 2
    capsys.readouterr()


def test_env_var_activates_plan(monkeypatch):
    from kindel_tpu.resilience import activate_from_env

    monkeypatch.setenv("KINDEL_TPU_FAULTS", "device.compile:error")
    plan = activate_from_env()
    assert plan is not None
    assert plan.specs[0].site == "device.compile"
    monkeypatch.setenv("KINDEL_TPU_FAULTS", "")
    rfaults.deactivate()
    assert activate_from_env() is None
