"""Worker for the two-process pod data-plane tests: join the localhost
JAX group (4 virtual CPU devices per process → 8 global) purely through
the `--mesh pod:<dp>` knob surface — the plan builder brings the group
up from the standard cluster env vars — then drive all three dispatch
tiers through the shared podfixture drivers and print the digests.

Usage:
  python tests/_dist_pod_worker.py <process_id> <port> <dp> <tmpdir> \
      [realign]

(underscore prefix: not collected by pytest)."""

import os
import sys

proc_id = int(sys.argv[1])
port = int(sys.argv[2])
dp = int(sys.argv[3])
tmpdir = sys.argv[4]
realign = len(sys.argv) > 5 and sys.argv[5] == "realign"

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
# the pod plan reads the standard cluster env vars — the knob surface
# under test is `--mesh pod:<dp>`, not an explicit initialize() call
os.environ["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
os.environ["JAX_NUM_PROCESSES"] = "2"
os.environ["JAX_PROCESS_ID"] = str(proc_id)
os.environ["KINDEL_TPU_MESH"] = f"pod:{dp}"
# isolate the tune/AOT store per process (never read the host's)
os.environ["KINDEL_TPU_TUNE_CACHE"] = os.path.join(
    tmpdir, f"proc{proc_id}", "tune.json"
)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_here))
sys.path.insert(0, _here)

from tests import podfixture  # noqa: E402
from kindel_tpu.parallel import meshexec  # noqa: E402

plan = meshexec.plan()
assert plan.procs == 2, f"pod group did not come up: {plan}"
assert plan.proc_id == proc_id
assert plan.dp == dp, f"requested dp {dp}, planned {plan.dp}"
assert jax.device_count() == 8, jax.device_count()

# the mesh must span both processes, each owning contiguous shard blocks
mesh = plan.mesh_for(plan.dp)
owners = [int(d.process_index) for d in mesh.devices.flat]
assert owners == sorted(owners) and set(owners) == {0, 1}, owners

digests = podfixture.all_digests(
    os.path.join(tmpdir, f"proc{proc_id}", "sams"), plan,
    realign=realign,
)
for tier, d in sorted(digests.items()):
    print(f"DIGEST:{tier}={d}", flush=True)
print(f"PODPLAN:dp={plan.dp},procs={plan.procs}", flush=True)
