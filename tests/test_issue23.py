"""The reference's SECOND disabled realign case, exceeded — with the
reference's own shipped expected output as the oracle.

/root/reference/tests/test_kindel.py:281-299 is commented out with
"Kindel 1.2 adds an unwanted insertion at 1284"; unlike the gp120 case,
its input (data_ext/3.issue23.bc75.sam) and curated expected output
(3.issue23.bc75.realign.fa) ARE shipped. Two boundary artifacts cause
the divergence, both fixed under --fix-clip-artifacts (default off =
reference-exact):

1. the insertion threshold `ins·2 > min(cur, next)` degenerates where
   the floor is zero (the last covered position before the clip-dominant
   dead zone): one stray insertion-carrying read fabricates a base;
2. the forward clip extension's first projected base duplicates the
   unambiguous aligned consensus at the flank (ambiguous aligner clip
   boundary), so the CDR patch re-emits a base the flank already carries
   — the reverse scan has lag compensation (kindel.py:257-261), the
   forward scan never did.
"""

from pathlib import Path

import pytest

from kindel_tpu.workloads import bam_to_consensus

BC75 = Path("/root/reference/tests/data_ext/3.issue23.bc75.sam")


def _expected() -> str:
    fa = BC75.with_suffix(".realign.fa")
    return "".join(
        l.strip() for l in fa.read_text().splitlines()
        if not l.startswith(">")
    ).upper()


pytestmark = pytest.mark.skipif(
    not BC75.exists(), reason="reference data_ext corpus unavailable"
)


@pytest.mark.parametrize("backend", ["numpy", "jax"])
def test_bc75_fixed_matches_reference_expected_output(backend):
    """`consensus -r --fix-clip-artifacts` must reproduce the reference's
    own curated expected output for its disabled issue23-bc75 case,
    byte-for-byte, on both backends."""
    res = bam_to_consensus(
        BC75, realign=True, min_overlap=7, backend=backend,
        fix_clip_artifacts=True,
    )
    assert res.consensuses[0].sequence.upper() == _expected()


def test_bc75_default_replicates_reference_bug():
    """Default output stays reference-exact: the documented unwanted
    insertion is present (one base longer than the curated expectation)
    — proving the fix is non-vacuous and parity is untouched."""
    res = bam_to_consensus(BC75, realign=True, min_overlap=7)
    got = res.consensuses[0].sequence.upper()
    want = _expected()
    assert got != want
    assert len(got) == len(want) + 1


def test_fix_leaves_enabled_realign_cases_untouched():
    """The two ENABLED data_ext realign cases (whose goldens the
    reference suite pins) must be byte-identical with the fix on — the
    artifact conditions do not fire there, so --fix-clip-artifacts is
    surgical, not a blanket behavior change."""
    for name in ("1.issue23.debug", "2.issue23.bc63"):
        sam = BC75.parent / f"{name}.sam"
        plain = bam_to_consensus(sam, realign=True, min_overlap=7)
        fixed = bam_to_consensus(
            sam, realign=True, min_overlap=7, fix_clip_artifacts=True
        )
        assert (
            fixed.consensuses[0].sequence == plain.consensuses[0].sequence
        ), name


def test_bc75_fixed_via_batch_cli(tmp_path):
    """--fix-clip-artifacts must be reachable from the batch subcommand
    (the cohort path's plumbing would otherwise be CLI-dead code)."""
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, "-m", "kindel_tpu", "batch", str(BC75),
         "-r", "--min-overlap", "7", "--fix-clip-artifacts",
         "-o", str(tmp_path)],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-800:]
    fa = tmp_path / "3.issue23.bc75.fa"
    got = "".join(
        l.strip() for l in fa.read_text().splitlines()
        if not l.startswith(">")
    ).upper()
    assert got == _expected()
