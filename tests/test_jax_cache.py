"""Persistent XLA compilation cache wiring (kindel_tpu/utils/jax_cache.py)."""

import warnings

import jax
import pytest

from kindel_tpu.utils import jax_cache


def test_cache_configured(tmp_path, monkeypatch):
    before = jax.config.jax_compilation_cache_dir
    monkeypatch.setenv("KINDEL_TPU_COMPILE_CACHE", str(tmp_path / "xla"))
    monkeypatch.setattr(jax_cache, "_done", False)
    try:
        jax_cache.ensure_compilation_cache()
        # an explicit KINDEL_TPU_COMPILE_CACHE=<dir> is used EXACTLY as
        # given (prewarmed caches must hit) — no fingerprint subdirectory
        assert jax.config.jax_compilation_cache_dir == str(tmp_path / "xla")
        assert (tmp_path / "xla").is_dir()
    finally:
        jax.config.update("jax_compilation_cache_dir", before)


def test_cache_respects_user_config(tmp_path, monkeypatch):
    before = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", str(tmp_path / "mine"))
    monkeypatch.delenv("KINDEL_TPU_COMPILE_CACHE", raising=False)
    monkeypatch.setattr(jax_cache, "_done", False)
    try:
        jax_cache.ensure_compilation_cache()
        assert jax.config.jax_compilation_cache_dir == str(tmp_path / "mine")
    finally:
        jax.config.update("jax_compilation_cache_dir", before)


def test_cache_disable(tmp_path, monkeypatch):
    before = jax.config.jax_compilation_cache_dir
    monkeypatch.setenv("KINDEL_TPU_COMPILE_CACHE", "off")
    monkeypatch.setattr(jax_cache, "_done", False)
    jax_cache.ensure_compilation_cache()
    # disabling must not clobber an unrelated existing setting
    assert jax.config.jax_compilation_cache_dir == before


def test_transient_failure_warns_once_and_does_not_latch(tmp_path,
                                                         monkeypatch):
    """A transient failure (unwritable cache dir) must not silently
    disable the cache for the rest of the process: `_done` latches only
    on success, the first failure warns once, and a later call with a
    healthy filesystem enables the cache."""
    before = jax.config.jax_compilation_cache_dir
    blocker = tmp_path / "blocker"
    blocker.write_text("")  # a FILE where the cache dir's parent must be
    monkeypatch.setenv("KINDEL_TPU_COMPILE_CACHE", str(blocker / "xla"))
    monkeypatch.setattr(jax_cache, "_done", False)
    monkeypatch.setattr(jax_cache, "_warned", False)
    try:
        with pytest.warns(RuntimeWarning, match="compile cache"):
            jax_cache.ensure_compilation_cache()
        assert jax_cache._done is False  # not latched: next call retries
        # second failing attempt retries but stays quiet (warn once)
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            jax_cache.ensure_compilation_cache()
        assert jax_cache._done is False
        # recovery: a writable location succeeds and latches
        monkeypatch.setenv("KINDEL_TPU_COMPILE_CACHE", str(tmp_path / "xla"))
        jax_cache.ensure_compilation_cache()
        assert jax_cache._done is True
        assert jax.config.jax_compilation_cache_dir == str(tmp_path / "xla")
    finally:
        jax.config.update("jax_compilation_cache_dir", before)


def test_success_and_noop_paths_latch(tmp_path, monkeypatch):
    """The deliberate no-op paths (cache off) latch too — they are
    decisions, not failures, and must not re-run per caller."""
    before = jax.config.jax_compilation_cache_dir
    monkeypatch.setenv("KINDEL_TPU_COMPILE_CACHE", "off")
    monkeypatch.setattr(jax_cache, "_done", False)
    try:
        jax_cache.ensure_compilation_cache()
        assert jax_cache._done is True
    finally:
        jax.config.update("jax_compilation_cache_dir", before)


def test_default_location_is_machine_tagged(tmp_path, monkeypatch):
    """The DEFAULT cache location gains a per-host fingerprint subdir on
    the CPU backend: XLA:CPU AOT entries embed the compile machine's
    feature set, and loading another host's entries warns of SIGILL and
    can be slower than a fresh compile."""
    before = jax.config.jax_compilation_cache_dir
    monkeypatch.delenv("KINDEL_TPU_COMPILE_CACHE", raising=False)
    monkeypatch.setenv("HOME", str(tmp_path))
    monkeypatch.setattr(jax_cache, "_done", False)
    try:
        jax.config.update("jax_compilation_cache_dir", None)
        jax_cache.ensure_compilation_cache()
        got = jax.config.jax_compilation_cache_dir
        tag = jax_cache._machine_tag(jax.__version__)
        assert got is not None and got.endswith(tag)
        assert str(tmp_path) in got
    finally:
        jax.config.update("jax_compilation_cache_dir", before)
