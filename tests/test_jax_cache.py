"""Persistent XLA compilation cache wiring (kindel_tpu/utils/jax_cache.py)."""

import jax

from kindel_tpu.utils import jax_cache


def test_cache_configured(tmp_path, monkeypatch):
    before = jax.config.jax_compilation_cache_dir
    monkeypatch.setenv("KINDEL_TPU_COMPILE_CACHE", str(tmp_path / "xla"))
    monkeypatch.setattr(jax_cache, "_done", False)
    try:
        jax_cache.ensure_compilation_cache()
        assert jax.config.jax_compilation_cache_dir == str(tmp_path / "xla")
        assert (tmp_path / "xla").is_dir()
    finally:
        jax.config.update("jax_compilation_cache_dir", before)


def test_cache_respects_user_config(tmp_path, monkeypatch):
    before = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", str(tmp_path / "mine"))
    monkeypatch.delenv("KINDEL_TPU_COMPILE_CACHE", raising=False)
    monkeypatch.setattr(jax_cache, "_done", False)
    try:
        jax_cache.ensure_compilation_cache()
        assert jax.config.jax_compilation_cache_dir == str(tmp_path / "mine")
    finally:
        jax.config.update("jax_compilation_cache_dir", before)


def test_cache_disable(tmp_path, monkeypatch):
    before = jax.config.jax_compilation_cache_dir
    monkeypatch.setenv("KINDEL_TPU_COMPILE_CACHE", "off")
    monkeypatch.setattr(jax_cache, "_done", False)
    jax_cache.ensure_compilation_cache()
    # disabling must not clobber an unrelated existing setting
    assert jax.config.jax_compilation_cache_dir == before
