"""numpy↔jax backend equivalence + mesh sharding tests.

Runs on a virtual 8-device CPU mesh (conftest sets
xla_force_host_platform_device_count) — SURVEY §4's TPU-world analogue of
the reference's real-data testing.
"""

import numpy as np
import pytest

from kindel_tpu.events import extract_events
from kindel_tpu.io import load_alignment
from kindel_tpu.pileup import build_pileups
from kindel_tpu.workloads import bam_to_consensus


@pytest.fixture(scope="module")
def bwa_events(data_root):
    return extract_events(
        load_alignment(data_root / "data_bwa_mem" / "1.1.sub_test.bam")
    )


def test_pileup_jax_equivalence(bwa_events):
    from kindel_tpu.pileup_jax import build_pileups_jax

    np_p = next(iter(build_pileups(bwa_events).values()))
    jx_p = next(iter(build_pileups_jax(bwa_events).values()))
    np.testing.assert_array_equal(np_p.weights, jx_p.weights)
    np.testing.assert_array_equal(np_p.clip_start_weights, jx_p.clip_start_weights)
    np.testing.assert_array_equal(np_p.clip_end_weights, jx_p.clip_end_weights)
    np.testing.assert_array_equal(np_p.clip_starts, jx_p.clip_starts)
    np.testing.assert_array_equal(np_p.clip_ends, jx_p.clip_ends)
    np.testing.assert_array_equal(np_p.deletions, jx_p.deletions)
    np.testing.assert_array_equal(np_p.ins.totals, jx_p.ins.totals)


def test_fused_call_equivalence(bwa_events):
    from kindel_tpu.call import call_consensus
    from kindel_tpu.call_jax import call_consensus_fused

    rid = bwa_events.present_ref_ids[0]
    pileup = next(iter(build_pileups(bwa_events).values()))
    np_res = call_consensus(pileup)
    jx_res, dmin, dmax = call_consensus_fused(bwa_events, rid, pileup=pileup)
    assert np_res.sequence == jx_res.sequence
    assert np_res.changes == jx_res.changes
    assert dmin == int(pileup.acgt_depth.min())
    assert dmax == int(pileup.acgt_depth.max())


@pytest.mark.parametrize("compact", ["1", "0"])
def test_emit_only_fast_path(bwa_events, compact, monkeypatch):
    """build_changes=False skips the dense mask download; sequence must be
    identical to the full-masks path — in both fast wire formats (the
    compact-covered wire degenerates to C≈L on this full-coverage BAM)."""
    from kindel_tpu.call_jax import call_consensus_fused

    monkeypatch.setenv("KINDEL_TPU_COMPACT_WIRE", compact)
    rid = bwa_events.present_ref_ids[0]
    full, _, _ = call_consensus_fused(bwa_events, rid, build_changes=True)
    fast, _, _ = call_consensus_fused(bwa_events, rid, build_changes=False)
    assert full.sequence == fast.sequence


def test_cli_backend_jax_matches_numpy(data_root):
    from tests.test_consensus_golden import run_consensus

    path = data_root / "data_minimap2" / "1.1.multi.bam"
    np_out = run_consensus(path)
    jx_out = run_consensus(path, "--backend", "jax")
    assert np_out == jx_out


def test_device_call_masks_match_numpy(bwa_events):
    from kindel_tpu.call import compute_masks
    from kindel_tpu.call_jax import device_call

    rid = bwa_events.present_ref_ids[0]
    pileup = next(iter(build_pileups(bwa_events).values()))
    L = pileup.ref_len
    np_masks = compute_masks(
        pileup.weights, pileup.deletions[:L],
        pileup.ins.totals[:L].astype(np.int64), min_depth=1,
    )
    emit, jx_masks, dmin, dmax = device_call(bwa_events, rid)
    np.testing.assert_array_equal(np_masks.base_char, jx_masks.base_char)
    np.testing.assert_array_equal(np_masks.del_mask, jx_masks.del_mask)
    np.testing.assert_array_equal(np_masks.n_mask, jx_masks.n_mask)
    np.testing.assert_array_equal(np_masks.ins_mask, jx_masks.ins_mask)
    assert dmin == int(pileup.acgt_depth.min())
    assert dmax == int(pileup.acgt_depth.max())


def test_sharded_call_equivalence(bwa_events):
    """Position-sharded (sp=8) fused call == numpy oracle, halo incl."""
    import jax

    from kindel_tpu.call import compute_masks
    from kindel_tpu.parallel import make_mesh, sharded_call

    assert len(jax.devices()) >= 8, "virtual device mesh missing"
    mesh = make_mesh({"sp": 8})
    rid = bwa_events.present_ref_ids[0]
    pileup = next(iter(build_pileups(bwa_events).values()))
    L = pileup.ref_len
    np_masks = compute_masks(
        pileup.weights, pileup.deletions[:L],
        pileup.ins.totals[:L].astype(np.int64), min_depth=1,
    )
    w_sharded, masks_sharded = sharded_call(bwa_events, rid, mesh)
    np.testing.assert_array_equal(w_sharded, pileup.weights)
    np.testing.assert_array_equal(masks_sharded.base_char, np_masks.base_char)
    np.testing.assert_array_equal(masks_sharded.del_mask, np_masks.del_mask)
    np.testing.assert_array_equal(masks_sharded.n_mask, np_masks.n_mask)
    np.testing.assert_array_equal(masks_sharded.ins_mask, np_masks.ins_mask)


def test_batched_dp_sp_step(bwa_events):
    """dp×sp batched step: two samples (same events) over a 2×4 mesh."""
    import numpy as np

    from kindel_tpu.call import compute_masks
    from kindel_tpu.parallel import make_mesh, batched_sharded_call

    mesh = make_mesh({"dp": 2, "sp": 4})
    rid = bwa_events.present_ref_ids[0]
    L = int(bwa_events.ref_lens[rid])
    sel = bwa_events.match_rid == rid
    sample = {
        "match_pos": bwa_events.match_pos[sel],
        "match_base": bwa_events.match_base[sel].astype(np.int64),
        "del_pos": bwa_events.del_pos[
            (bwa_events.del_rid == rid) & (bwa_events.del_pos < L)
        ],
        "ins_pos": np.empty(0, dtype=np.int64),
        "ins_cnt": np.empty(0, dtype=np.int64),
    }
    w, bc, dm, nm, im = batched_sharded_call([sample, sample], L, mesh)
    pileup = next(iter(build_pileups(bwa_events).values()))
    np_masks = compute_masks(
        pileup.weights, pileup.deletions[:L],
        np.zeros(L, dtype=np.int64),  # insertions excluded from the batch
        min_depth=1,
    )
    np.testing.assert_array_equal(w[0], pileup.weights)
    np.testing.assert_array_equal(w[0], w[1])
    np.testing.assert_array_equal(bc[0], np_masks.base_char)
    np.testing.assert_array_equal(dm[0], np_masks.del_mask)


def test_jax_realign_on_device_no_host_pileup(data_root, monkeypatch):
    """VERDICT r2 item 3: backend=jax --realign must not build a dense
    host pileup anywhere — single-device included (the product path runs
    on a 1-shard mesh under KINDEL_TPU_FORCE_FUSED). build_pileup is
    poisoned to prove it."""
    import kindel_tpu.pileup as pileup_mod
    import kindel_tpu.workloads as workloads_mod

    bam = data_root / "data_bwa_mem" / "1.1.sub_test.bam"
    expected = bam_to_consensus(bam, realign=True, min_overlap=7)

    def poisoned(*a, **k):
        raise AssertionError("dense host pileup built under backend=jax")

    monkeypatch.setattr(pileup_mod, "build_pileup", poisoned)
    monkeypatch.setattr(workloads_mod, "build_pileups", poisoned)

    for force_fused in ("", "1"):
        if force_fused:
            monkeypatch.setenv("KINDEL_TPU_FORCE_FUSED", force_fused)
        got = bam_to_consensus(
            bam, realign=True, min_overlap=7, backend="jax"
        )
        assert [c.sequence for c in got.consensuses] == [
            c.sequence for c in expected.consensuses
        ]
        assert got.refs_reports == expected.refs_reports


def test_jax_realign_streamed_single_device(data_root, monkeypatch):
    """Single-device streamed jax realign routes through the 1-shard
    sharded accumulator (no host pileup) and stays byte-identical."""
    from kindel_tpu.streaming import streamed_consensus

    bam = data_root / "data_bwa_mem" / "1.1.sub_test.bam"
    expected = bam_to_consensus(bam, realign=True, min_overlap=7)
    monkeypatch.setenv("KINDEL_TPU_FORCE_FUSED", "1")
    got = streamed_consensus(
        bam, realign=True, min_overlap=7, backend="jax",
        chunk_bytes=64 << 10,
    )
    assert [c.sequence for c in got.consensuses] == [
        c.sequence for c in expected.consensuses
    ]
    assert got.refs_reports == expected.refs_reports


def test_batch_realign_no_host_pileup(data_root, monkeypatch):
    """The cohort realign path reduces clip channels on device and walks
    them lazily — one poisoned build_pileup proves no per-sample host
    pileup is ever constructed."""
    import kindel_tpu.pileup as pileup_mod
    from kindel_tpu.batch import batch_bam_to_results

    bam = data_root / "data_bwa_mem" / "1.1.sub_test.bam"
    expected = bam_to_consensus(bam, realign=True)

    def poisoned(*a, **k):
        raise AssertionError("host pileup built in the cohort realign path")

    monkeypatch.setattr(pileup_mod, "build_pileup", poisoned)
    got = batch_bam_to_results([bam], realign=True)[bam]
    assert [c.sequence for c in got.consensuses] == [
        c.sequence for c in expected.consensuses
    ]
    assert got.refs_reports == expected.refs_reports


def test_multi_contig_fused_batched_identity(data_root, monkeypatch):
    """Multi-contig files on the single-device fused path run ONE
    batched dispatch for all contigs; output (sequences, changes,
    reports) must equal numpy exactly. FORCE_FUSED pins the fused route
    on the virtual mesh, where sharding would otherwise take over."""
    import kindel_tpu.workloads as w

    monkeypatch.setenv("KINDEL_TPU_FORCE_FUSED", "1")
    calls = []
    orig = w._fused_contig_batch

    def spy(*a, **k):
        out = orig(*a, **k)
        calls.append(len(out))
        return out

    monkeypatch.setattr(w, "_fused_contig_batch", spy)
    for rel in (("data_minimap2", "1.1.multi.bam"),):
        bam = data_root.joinpath(*rel)
        ref = bam_to_consensus(bam, backend="numpy")
        got = bam_to_consensus(bam, backend="jax")
        assert calls and calls[-1] > 1, "batched contig dispatch not taken"
        assert [c.sequence for c in got.consensuses] == [
            c.sequence for c in ref.consensuses
        ]
        assert got.refs_changes == ref.refs_changes
        assert got.refs_reports == ref.refs_reports


def test_fused_batch_groups_footprint():
    """Grouping must not let one long contig inflate every row's
    padding (review r3): a 6 Mb chromosome + tiny plasmids yields
    separate groups, and an over-limit contig becomes a singleton."""
    from types import SimpleNamespace

    import kindel_tpu.workloads as w
    from kindel_tpu.pileup_jax import MAX_PAD_SAFE_BLOCK

    ev = SimpleNamespace(ref_lens=[6_000_000] + [5_000] * 50)
    groups = w._fused_batch_groups(ev, list(range(51)))
    by_rid = {rid: g for g in groups for rid in g}
    assert len(by_rid) == 51
    # the chromosome does not share a group with 50 plasmids at its pad
    assert len(by_rid[0]) < 50
    assert all(rid in by_rid for rid in range(51))
    # footprint bound holds for every group
    from kindel_tpu.events import N_CHANNELS
    from kindel_tpu.pileup_jax import _bucket

    for g in groups:
        Lb = _bucket(max(int(ev.ref_lens[r]) for r in g), 1024)
        assert (
            len(g) == 1
            or len(g) * Lb * N_CHANNELS * 4 <= w._BATCH_SCATTER_BUDGET
        )
    # a contig past the PAD_POS limit is always a singleton
    ev2 = SimpleNamespace(ref_lens=[MAX_PAD_SAFE_BLOCK + 10, 1000, 2000])
    groups2 = w._fused_batch_groups(ev2, [0, 1, 2])
    assert [0] in groups2


def _sam(ref_len, reads):
    lines = [b"@HD\tVN:1.6", f"@SQ\tSN:ref1\tLN:{ref_len}".encode()]
    for i, (pos1, cigar, seq) in enumerate(reads):
        lines.append(
            f"r{i}\t0\tref1\t{pos1}\t60\t{cigar}\t*\t0\t0\t{seq}\t*".encode()
        )
    return b"\n".join(lines) + b"\n"


@pytest.mark.parametrize("compact", ["1", "0"])
@pytest.mark.parametrize("min_depth", [1, 2])
def test_compact_wire_low_coverage_edges(min_depth, compact, monkeypatch):
    """The compact-covered wire (device_call fast path) on a sparse layout
    exercising every branch the compaction must preserve: uncovered gaps
    (→ N), a deletion whose span has zero match depth (→ skip, recovered
    from sparse del flags), a tie (→ N among covered), a depth-1 site
    under min_depth=2 (→ N among covered), and an insertion."""
    from kindel_tpu.call import call_consensus
    from kindel_tpu.io.sam import parse_sam_bytes
    from kindel_tpu.call_jax import call_consensus_fused
    from kindel_tpu.pileup import build_pileups

    monkeypatch.setenv("KINDEL_TPU_COMPACT_WIRE", compact)
    reads = [
        (11, "6M", "ACGTAC"),          # island 1: covered 10..16
        (11, "6M", "ACGTAC"),          # depth 2 on island 1
        (31, "3M4D3M", "GGGTTT"),      # island 2 with an internal del span
        (51, "2M", "AA"),              # island 3: depth 1 (N under md=2)
        (61, "2M", "CC"),              # tie partner 1
        (61, "2M", "GG"),              # tie partner 2 → N,N
        (13, "2M2I2M", "GTACTA"),      # insertion inside island 1
    ]
    ev = extract_events(parse_sam_bytes(_sam(100, reads)))
    pileup = next(iter(build_pileups(ev).values()))
    rid = ev.present_ref_ids[0]
    np_res = call_consensus(
        pileup, min_depth=min_depth, build_changes=False
    )
    jx_res, dmin, dmax = call_consensus_fused(
        ev, rid, pileup=pileup, min_depth=min_depth, build_changes=False
    )
    assert np_res.sequence == jx_res.sequence
    assert dmin == int(pileup.acgt_depth.min())
    assert dmax == int(pileup.acgt_depth.max())
    # non-vacuity: the layout really has gaps, a del island, and a tie
    assert "NNN" in np_res.sequence


def test_covered_intervals_merge():
    from kindel_tpu.call_jax import covered_index, covered_intervals

    # overlapping, contained, adjacent, and disjoint spans in scrambled order
    starts = np.array([20, 0, 3, 8, 40, 5], dtype=np.int64)
    lens = np.array([5, 5, 4, 2, 1, 5], dtype=np.int64)
    m_starts, m_ends = covered_intervals(starts, lens)
    expect = np.zeros(64, dtype=bool)
    for s, n in zip(starts, lens):
        expect[s : s + n] = True
    got = np.zeros(64, dtype=bool)
    for s, e in zip(m_starts, m_ends):
        assert e > s
        got[s:e] = True
    np.testing.assert_array_equal(got, expect)
    np.testing.assert_array_equal(covered_index(starts, lens), np.flatnonzero(expect))
    # empty and zero-length spans
    z_starts, z_ends = covered_intervals(
        np.array([7], dtype=np.int64), np.array([0], dtype=np.int64)
    )
    assert len(z_starts) == 0 and len(z_ends) == 0


def test_slab_pipeline_matches_single(data_root, monkeypatch):
    """The slab-pipelined path (KINDEL_TPU_SLABS) must be byte-identical
    to the single-kernel fused path on the bacterial-scale BAM — slab
    boundaries, the depth_next halo, per-slab del/ins flag remapping, and
    the depth-scalar combine all pinned. Uses the real 6.1 Mb BAM so
    slabs are non-trivial (>64k positions each)."""
    from kindel_tpu.call_jax import call_consensus_fused
    from kindel_tpu.pileup import build_pileups

    bam = data_root / "data_minimap2_bact" / "bact.tiny.bam"
    ev = extract_events(load_alignment(bam))
    rid = ev.present_ref_ids[0]
    pileup = next(iter(build_pileups(ev).values()))

    monkeypatch.setenv("KINDEL_TPU_COMPACT_WIRE", "1")
    monkeypatch.setenv("KINDEL_TPU_SLABS", "1")  # true single-kernel anchor
    single, dmin1, dmax1 = call_consensus_fused(
        ev, rid, build_changes=False
    )
    # pin the compact path against the numpy oracle on real data with
    # N-carrying reads (N-only-covered positions shift compact slots if
    # the device covered-set definition drifts from the host span union)
    from kindel_tpu.call import call_consensus

    oracle = call_consensus(pileup, build_changes=False)
    assert single.sequence == oracle.sequence
    for n in (2, 5, 8):
        monkeypatch.setenv("KINDEL_TPU_SLABS", str(n))
        piped, dmin2, dmax2 = call_consensus_fused(
            ev, rid, build_changes=False
        )
        assert piped.sequence == single.sequence, f"n_slabs={n}"
        assert (dmin2, dmax2) == (dmin1, dmax1), f"n_slabs={n}"
    assert dmin1 == int(pileup.acgt_depth.min())
    assert dmax1 == int(pileup.acgt_depth.max())


@pytest.mark.parametrize("compact", ["1", "0"])
def test_slab_pipeline_synthetic_edges(monkeypatch, compact):
    """Slab pipeline on a synthetic layout where events straddle the
    exact slab boundary: spans crossing, a deletion at the boundary, and
    an insertion whose depth_next denominator crosses into the next
    slab. L=131072*2 so two 64k+ slabs are allowed."""
    from kindel_tpu.call_jax import call_consensus_fused
    from kindel_tpu.io.sam import parse_sam_bytes

    L = 262144
    B = 131072  # slab boundary with n_slabs=2
    reads = [
        (B - 2, "8M", "ACGTACGT"),          # straddles the boundary
        (B - 2, "8M", "ACGTACGT"),
        (B - 3, "3M2D3M", "TTTGGG"),        # deletion spanning boundary
        (B, "2M2I2M", "CCAATT"),            # insertion right at boundary
        (B - 1, "2M", "TA"),                # depth_next across boundary
        (100, "4M", "GGGG"),                # far-away island in slab 0
    ]
    monkeypatch.setenv("KINDEL_TPU_COMPACT_WIRE", compact)
    monkeypatch.setenv("KINDEL_TPU_SLABS", "1")  # single-kernel baseline
    ev = extract_events(parse_sam_bytes(_sam(L, reads)))
    rid = ev.present_ref_ids[0]
    single, d1, x1 = call_consensus_fused(ev, rid, build_changes=False)
    monkeypatch.setenv("KINDEL_TPU_SLABS", "2")
    piped, d2, x2 = call_consensus_fused(ev, rid, build_changes=False)
    assert piped.sequence == single.sequence
    assert (d1, x1) == (d2, x2)
