"""Differential fuzz: the REFERENCE implementation as a live oracle.

The reference's accumulator and caller are pure functions over plain
record objects (`parse_records(ref_id, ref_len, records)`,
`consensus_sequence(...)` — /root/reference/kindel/kindel.py:21,384), so
they can be driven directly with synthetic reads — no simplesam/BAM
needed — and compared field-by-field against this framework's dense
pileup and call path on the same reads rendered as SAM. This pins the
gnarliest replicated semantics (negative-index clip wrap-around,
trailing-clip clamping, insertion anchoring, tie→N, min(cur,next) indel
thresholds, CDR detection/extension/LCS-merge) on inputs far outside the
golden corpus.

CIGAR `N` is excluded from the generator: ref-skip handling is a
documented conscious divergence (see kindel_tpu/events.py).
"""

from __future__ import annotations

import importlib
import random
import sys
import types

import numpy as np
import pytest

from kindel_tpu.events import extract_events
from kindel_tpu.io.sam import parse_sam_bytes
from kindel_tpu.pileup import build_pileups
from kindel_tpu.workloads import bam_to_consensus

BASES4 = "ATGC"


# ---------------------------------------------------------------- oracle


def _load_reference_kindel():
    """Import /root/reference/kindel/kindel.py with stubs for the deps the
    container lacks (simplesam, dnaio, argh). Read-only import; nothing in
    the reference tree is executed beyond module definitions."""
    for name in ("simplesam", "dnaio", "argh"):
        # stub only what is genuinely absent — if the real package is ever
        # installed, it must win (a crippled stub in sys.modules would
        # poison later imports elsewhere in the process)
        if name not in sys.modules and importlib.util.find_spec(name) is None:
            stub = types.ModuleType(name)
            if name == "dnaio":
                class _Seq:  # minimal dnaio.Sequence stand-in
                    def __init__(self, name="", sequence="", qualities=None):
                        self.name = name
                        self.sequence = sequence
                        self.qualities = qualities
                stub.Sequence = _Seq
            if name == "argh":
                stub.arg = lambda *a, **k: (lambda f: f)
                stub.ArghParser = type("ArghParser", (), {})
                stub.dispatch = lambda *a, **k: None
            sys.modules[name] = stub
    # must be importable under its real name: the reference's cli.py does
    # absolute `from kindel import ...` imports. The name is free in this
    # process (the refsuite's `kindel` alias only exists in its own
    # subprocess run). The real package __init__ is 3 lines of metadata.
    sys.path.insert(0, "/root/reference")
    try:
        return importlib.import_module("kindel.kindel")
    finally:
        sys.path.remove("/root/reference")


try:
    REF = _load_reference_kindel()
except Exception as e:  # reference tree unavailable → skip whole module
    REF = None
    _REF_ERR = e

pytestmark = pytest.mark.skipif(
    REF is None, reason="reference implementation not importable"
)


class FakeRecord:
    """The record-API surface parse_records touches: pos (1-based), mapped,
    seq, rname, cigars as (length, op) pairs."""

    def __init__(self, pos1, seq, cigars, rname="ref1", mapped=True):
        self.pos = pos1
        self.seq = seq
        self.cigars = cigars
        self.rname = rname
        self.mapped = mapped

    def cigar_str(self):
        return "".join(f"{ln}{op}" for ln, op in self.cigars)


# ------------------------------------------------------------- generator


def random_read(rng: random.Random, ref_len: int):
    """One structurally-valid read: optional leading clip, M/I/D middle,
    optional trailing clip (possibly overhanging the reference end, which
    the reference clamps)."""
    cigars = []
    parts = []
    pos1 = rng.randint(1, max(ref_len - 10, 1))
    if rng.random() < 0.35:  # leading soft clip; wraps negative at pos 1-3
        ln = rng.randint(1, 8)
        cigars.append((ln, "S"))
        parts.append("".join(rng.choice(BASES4) for _ in range(ln)))
    n_mid = rng.randint(1, 4)
    ref_left = ref_len - (pos1 - 1)
    for i in range(n_mid):
        op = "M" if i == 0 else rng.choice("MID")
        ln = rng.randint(1, 12)
        if op in "MD":
            ln = max(min(ln, ref_left - 1), 1)
            if ref_left <= 1:
                break
            ref_left -= ln
        cigars.append((ln, op))
        if op in "MI":
            parts.append("".join(rng.choice(BASES4) for _ in range(ln)))
    if rng.random() < 0.35:  # trailing clip, sometimes overhanging
        ln = rng.randint(1, 12)
        cigars.append((ln, "S"))
        parts.append("".join(rng.choice(BASES4) for _ in range(ln)))
    # merge adjacent same-op runs (valid CIGAR) and ensure >=1 M
    merged = []
    for ln, op in cigars:
        if merged and merged[-1][1] == op:
            merged[-1][0] += ln
        else:
            merged.append([ln, op])
    cigars = [(ln, op) for ln, op in merged]
    if not any(op == "M" for _, op in cigars):
        return None
    seq = "".join(parts)
    if len(seq) <= 1:
        return None
    return FakeRecord(pos1, seq, cigars)


def random_alignment(seed: int):
    rng = random.Random(seed)
    ref_len = rng.randint(30, 200)
    reads = []
    for _ in range(rng.randint(2, 30)):
        r = random_read(rng, ref_len)
        if r is not None:
            reads.append(r)
    if not reads:
        reads = [FakeRecord(1, "ACGTACGT", [(8, "M")])]
    return ref_len, reads


def to_sam(ref_len: int, reads) -> bytes:
    lines = [b"@HD\tVN:1.6", f"@SQ\tSN:ref1\tLN:{ref_len}".encode()]
    for i, r in enumerate(reads):
        lines.append(
            f"r{i}\t0\tref1\t{r.pos}\t60\t{r.cigar_str()}\t*\t0\t0\t"
            f"{r.seq}\t*".encode()
        )
    return b"\n".join(lines) + b"\n"


# ------------------------------------------------------------------ tests


@pytest.mark.parametrize("seed", range(40))
def test_accumulator_matches_reference(seed):
    ref_len, reads = random_alignment(seed)
    aln = REF.parse_records("ref1", ref_len, reads)

    ev = extract_events(parse_sam_bytes(to_sam(ref_len, reads)))
    p = next(iter(build_pileups(ev).values()))

    for pos in range(ref_len):
        for b_i, b in enumerate("ATGCN"):
            assert p.weights[pos, b_i] == aln.weights[pos][b], (
                f"weights[{pos}][{b}] seed={seed}"
            )
            assert (
                p.clip_start_weights[pos, b_i]
                == aln.clip_start_weights[pos][b]
            ), f"csw[{pos}][{b}] seed={seed}"
            assert (
                p.clip_end_weights[pos, b_i] == aln.clip_end_weights[pos][b]
            ), f"cew[{pos}][{b}] seed={seed}"
    assert p.deletions[: ref_len + 1].tolist() == list(aln.deletions)
    assert p.clip_starts[: ref_len + 1].tolist() == list(aln.clip_starts)
    assert p.clip_ends[: ref_len + 1].tolist() == list(aln.clip_ends)
    for pos in range(ref_len + 1):
        ours = {
            s.decode(): c
            for (rid, ppos, s), c in ev.insertions.items()
            if ppos == pos
        }
        assert ours == dict(aln.insertions[pos]), f"ins[{pos}] seed={seed}"


def test_negative_index_wraparound_matches_reference():
    """A trailing clip with zero reference consumed before it makes the
    reference write clip_starts[-1] — Python wrap-around to the array's
    last slot (ref kindel.py:76), replicated by events._wrap. The random
    generator never emits this shape (M always leads), so pin it
    explicitly."""
    ref_len = 40
    reads = [
        FakeRecord(1, "ACGTTTTT", [(3, "I"), (5, "S")]),
        FakeRecord(1, "ACGTACGTA", [(4, "M"), (5, "S")]),
    ]
    aln = REF.parse_records("ref1", ref_len, reads)
    ev = extract_events(parse_sam_bytes(to_sam(ref_len, reads)))
    p = next(iter(build_pileups(ev).values()))
    assert aln.clip_starts[ref_len] == 1  # the wrapped write landed
    assert p.clip_starts[: ref_len + 1].tolist() == list(aln.clip_starts)
    assert p.clip_ends[: ref_len + 1].tolist() == list(aln.clip_ends)
    for pos in range(ref_len):
        for b_i, b in enumerate("ATGCN"):
            assert p.weights[pos, b_i] == aln.weights[pos][b]
            assert (
                p.clip_start_weights[pos, b_i]
                == aln.clip_start_weights[pos][b]
            )


@pytest.mark.parametrize("seed", range(40))
@pytest.mark.parametrize("realign", [False, True])
def test_consensus_matches_reference(seed, realign, tmp_path):
    ref_len, reads = random_alignment(seed)
    aln = REF.parse_records("ref1", ref_len, reads)

    cdr_patches = None
    if realign:
        cdrps = REF.cdrp_consensuses(
            aln.weights, aln.deletions, aln.clip_start_weights,
            aln.clip_end_weights, aln.clip_start_depth, aln.clip_end_depth,
            0.1, 10,
        )
        cdr_patches = REF.merge_cdrps(cdrps, 7)
    ref_seq, ref_changes = REF.consensus_sequence(
        aln.weights, aln.insertions, aln.deletions, cdr_patches,
        trim_ends=False, min_depth=1, uppercase=False,
    )

    sam = tmp_path / f"fuzz{seed}.sam"
    sam.write_bytes(to_sam(ref_len, reads))
    res = bam_to_consensus(
        sam, realign=realign, min_depth=1, min_overlap=7,
        clip_decay_threshold=0.1, mask_ends=10, trim_ends=False,
        uppercase=False,
    )
    ours = res.consensuses[0].sequence
    assert ours == ref_seq, f"seed={seed} realign={realign}"
    assert res.refs_changes["ref1"] == ref_changes


def cdr_heavy_alignment(seed: int):
    """Alignment engineered to trigger the realign pipeline: a coverage
    GAP (bp1, bp2) that only soft-clip projections span — left-anchored
    reads match up to bp1 then clip rightward across the gap,
    right-anchored reads clip leftward across it then match from bp2.
    Inside the gap csd ≫ w, so the dominance trigger fires; the clips
    share the gap sequence, so pairing + LCS merge run (gap < min_overlap
    exercises the merge-failure → unpatched fallback too)."""
    rng = random.Random(seed + 7_000_000)
    ref_len = rng.randint(90, 220)
    gap = rng.randint(4, 18)  # straddles min_overlap=7: merges + failures
    bp1 = rng.randint(20, ref_len - 30 - gap)
    bp2 = bp1 + gap
    gap_seq = "".join(rng.choice(BASES4) for _ in range(gap))
    flank_l = "".join(rng.choice(BASES4) for _ in range(25))
    flank_r = "".join(rng.choice(BASES4) for _ in range(25))
    reads = []
    depth = rng.randint(4, 9)
    for _ in range(depth):
        # → side: match the left flank up to bp1, clip across the gap and
        # a few bases into the right flank
        m = rng.randint(8, 20)
        k = rng.randint(0, 6)
        clip = gap_seq + flank_r[:k]
        seq = flank_l[-m:] + clip
        reads.append(
            FakeRecord(bp1 - m + 1, seq, [(m, "M"), (len(clip), "S")])
        )
    for _ in range(depth):
        # ← side: clip out of the left flank + gap, match from bp2+1 on
        m = rng.randint(8, 20)
        k = rng.randint(0, 6)
        clip = flank_l[-k:] + gap_seq if k else gap_seq
        seq = clip + flank_r[:m]
        reads.append(
            FakeRecord(bp2 + 1, seq, [(len(clip), "S"), (m, "M")])
        )
    return ref_len, reads


@pytest.mark.parametrize("seed", range(30))
def test_cdr_heavy_realign_matches_reference(seed, tmp_path):
    """Targeted CDR fuzz: detection, pairing, decay extension, and the
    LCS merge (including min_overlap failures → unpatched fallback) must
    match the reference on clip-dominant inputs."""
    ref_len, reads = cdr_heavy_alignment(seed)
    aln = REF.parse_records("ref1", ref_len, reads)
    cdrps = REF.cdrp_consensuses(
        aln.weights, aln.deletions, aln.clip_start_weights,
        aln.clip_end_weights, aln.clip_start_depth, aln.clip_end_depth,
        0.1, 10,
    )
    cdr_patches = REF.merge_cdrps(cdrps, 7)
    assert cdr_patches, "generator failed to trigger a CDR (vacuous test)"
    ref_seq, ref_changes = REF.consensus_sequence(
        aln.weights, aln.insertions, aln.deletions, cdr_patches,
        trim_ends=False, min_depth=1, uppercase=False,
    )

    sam = tmp_path / f"cdr{seed}.sam"
    sam.write_bytes(to_sam(ref_len, reads))
    for backend in ("numpy", "jax"):
        res = bam_to_consensus(
            sam, realign=True, min_depth=1, min_overlap=7,
            clip_decay_threshold=0.1, mask_ends=10, trim_ends=False,
            uppercase=False, backend=backend,
        )
        assert res.consensuses[0].sequence == ref_seq, (seed, backend)
        assert res.refs_changes["ref1"] == ref_changes, (seed, backend)


_FUZZ_ORACLES: dict = {}


@pytest.mark.parametrize("force_fused", ["1", ""])
@pytest.mark.parametrize("seed", range(6))
def test_fused_vs_oracle_fuzz_slab_scale(seed, force_fused, tmp_path,
                                         monkeypatch):
    """End-to-end jax-vs-numpy consensus equality on randomized
    alignments at slab-exercising reference lengths (>=2 slabs after the
    64k clamp): random sparse coverage, indels, clips, N bases, reads at
    the extreme ends — the compact-covered wire and slab boundaries see
    arbitrary geometry, not just the curated corpus. force_fused pins
    the single-device slab pipeline; without it the 8-device mesh
    routes through the sharded product path, so both jax routes fuzz."""
    from kindel_tpu.workloads import bam_to_consensus

    if force_fused:
        monkeypatch.setenv("KINDEL_TPU_FORCE_FUSED", force_fused)
    else:
        # an ambient export would silently pin BOTH legs to the fused
        # path and the sharded route would go untested
        monkeypatch.delenv("KINDEL_TPU_FORCE_FUSED", raising=False)

    rng = np.random.default_rng(1000 + seed)
    L = int(rng.integers(140_000, 400_000))
    lines = [b"@HD\tVN:1.6", f"@SQ\tSN:fz\tLN:{L}".encode()]
    n_reads = int(rng.integers(30, 120))
    for i in range(n_reads):
        rl = int(rng.integers(40, 180))
        pos = int(rng.integers(0, L - rl))
        seq = "".join("ACGTN"[b] for b in rng.choice(
            5, size=rl, p=[0.24, 0.24, 0.24, 0.24, 0.04]
        ))
        roll = rng.random()
        m = rl - 12
        if roll < 0.2:
            cigar = f"6S{m}M6S"
        elif roll < 0.4:
            cigar = f"{m // 2}M{rl - m}D{m - m // 2}M"
            seq = seq[:m]
        elif roll < 0.55:
            cigar = f"{m // 2}M{rl - m}I{m - m // 2}M"
        else:
            cigar = f"{rl}M"
        lines.append(
            f"r{i}\t0\tfz\t{pos + 1}\t60\t{cigar}\t*\t0\t0\t{seq}\t*".encode()
        )
    # pin reads at both extreme ends (slab 0 head, last slab tail)
    lines.append(f"re0\t0\tfz\t1\t60\t50M\t*\t0\t0\t{'A' * 50}\t*".encode())
    lines.append(
        f"re1\t0\tfz\t{L - 49}\t60\t50M\t*\t0\t0\t{'C' * 50}\t*".encode()
    )
    # the oracle (and the SAM path, which the report text embeds) is
    # independent of force_fused — compute once per seed and share the
    # file across both legs so report comparison stays byte-exact
    if seed not in _FUZZ_ORACLES:
        sam = tmp_path / "fuzz.sam"
        sam.write_bytes(b"\n".join(lines) + b"\n")
        _FUZZ_ORACLES[seed] = (sam, bam_to_consensus(sam, backend="numpy"))
    sam, np_res = _FUZZ_ORACLES[seed]
    jx_res = bam_to_consensus(sam, backend="jax")
    assert (
        np_res.consensuses[0].sequence == jx_res.consensuses[0].sequence
    ), f"seed={seed} L={L}"
    assert np_res.refs_reports == jx_res.refs_reports
