"""Position-sharded product path (kindel_tpu.parallel.product).

The contract (VERDICT r1, next-round item 3): non-realign AND realign
consensus must be byte-identical through the sharded path on the 8-device
CPU mesh — sequence, changes, and report text — against the numpy oracle,
which itself is pinned to the reference by the golden and differential
suites.
"""

import os
from pathlib import Path

import numpy as np
import pytest

import jax

from kindel_tpu.events import extract_events
from kindel_tpu.io import load_alignment
from kindel_tpu.parallel import make_mesh, sharded_consensus, split_match_spans
from kindel_tpu.parallel.product import ShardedRef
from kindel_tpu.workloads import bam_to_consensus


# NB: not imported from conftest — importing `tests.conftest` would execute
# the module body a second time under a new name (relay probe, re-exec
# guard, jax-import watchdog).
_DATA_ROOT = Path(
    os.environ.get("KINDEL_TPU_TEST_DATA", "/root/reference/tests")
)


def require_data(*rel) -> Path:
    path = _DATA_ROOT.joinpath(*rel)
    if not path.exists():
        pytest.skip(f"golden corpus not available: {path}")
    return path


def _events(path):
    return extract_events(load_alignment(path))


# ---------------------------------------------------------------------------
# split_match_spans unit behavior
# ---------------------------------------------------------------------------


def test_split_match_spans_reconstructs_counts():
    rng = np.random.default_rng(7)
    L, n, block = 1000, 4, 256  # Lp=1024
    # spans of varying length, some crossing block boundaries
    starts = rng.integers(0, L - 60, size=50)
    lens = rng.integers(1, 60, size=50)
    mp = np.concatenate([np.arange(s, s + l) for s, l in zip(starts, lens)])
    mb = rng.integers(0, 5, size=len(mp)).astype(np.uint8)

    op_start, op_off, base_packed, n_ev = split_match_spans(mp, mb, n, block)
    assert int(n_ev.sum()) == len(mp)

    # reconstruct (pos, base) multiset per shard and compare to a direct
    # host bincount of the same events
    expect = np.zeros((n * block, 5), np.int64)
    np.add.at(expect, (mp, mb.astype(np.int64)), 1)
    got = np.zeros((n * block, 5), np.int64)
    for s in range(n):
        E = int(n_ev[s])
        bases = np.empty(base_packed.shape[1] * 2, np.uint8)
        bases[0::2] = base_packed[s] >> 4
        bases[1::2] = base_packed[s] & 0xF
        offs = op_off[s]
        for j in range(op_start.shape[1]):
            if op_start[s, j] >= block:  # padding (PAD_POS)
                continue
            end = min(offs[j + 1] if j + 1 < len(offs) else E, E)
            for i in range(offs[j], end):
                pos = s * block + op_start[s, j] + (i - offs[j])
                got[pos, bases[i]] += 1
    assert np.array_equal(got, expect)


def test_split_match_spans_empty():
    op_start, op_off, base_packed, n_ev = split_match_spans(
        np.empty(0, np.int64), np.empty(0, np.uint8), 4, 64
    )
    assert n_ev.sum() == 0
    assert op_start.shape[0] == 4


# ---------------------------------------------------------------------------
# ShardedRef counts equal the host pileup
# ---------------------------------------------------------------------------


def test_sharded_counts_match_host_pileup():
    from kindel_tpu.pileup import build_pileup

    bam = require_data("data_bwa_mem", "1.1.sub_test.bam")
    ev = _events(bam)
    rid = ev.present_ref_ids[0]
    host = build_pileup(ev, rid)
    mesh = make_mesh()
    sr = ShardedRef(ev, rid, mesh, realign=True)
    L = sr.L
    assert np.array_equal(sr.window("weights", 0, L), host.weights)
    assert np.array_equal(sr.window("deletions", 0, L), host.deletions[:L])
    assert np.array_equal(sr.window("csw", 0, L), host.clip_start_weights)
    assert np.array_equal(sr.window("cew", 0, L), host.clip_end_weights)
    assert np.array_equal(
        sr.window("ins_totals", 0, L), host.ins.totals[:L].astype(np.int32)
    )
    dmin, dmax = sr.depth_scalars()
    acgt = host.acgt_depth
    assert (dmin, dmax) == (int(acgt.min()), int(acgt.max()))


# ---------------------------------------------------------------------------
# end-to-end byte identity vs the numpy oracle
# ---------------------------------------------------------------------------

BWA = ["1.1", "2.1", "3.1", "4.1", "5.1", "6.1"]


def _assert_products_equal(a, b):
    assert [s.sequence for s in a.consensuses] == [
        s.sequence for s in b.consensuses
    ]
    assert a.refs_changes == b.refs_changes
    assert a.refs_reports == b.refs_reports


@pytest.mark.parametrize("sample", BWA)
@pytest.mark.parametrize("realign", [False, True])
def test_sharded_matches_numpy_bwa(sample, realign):
    bam = require_data("data_bwa_mem", f"{sample}.sub_test.bam")
    assert len(jax.devices()) == 8  # the virtual CPU mesh must be active
    got = bam_to_consensus(bam, realign=realign, backend="jax")
    want = bam_to_consensus(bam, realign=realign, backend="numpy")
    _assert_products_equal(got, want)


@pytest.mark.parametrize("realign", [False, True])
def test_sharded_matches_numpy_multicontig(realign):
    bam = require_data("data_minimap2", "1.1.multi.bam")
    got = bam_to_consensus(bam, realign=realign, backend="jax")
    want = bam_to_consensus(bam, realign=realign, backend="numpy")
    _assert_products_equal(got, want)


def test_sharded_matches_numpy_ext_sam():
    sam = require_data("data_ext", "1.issue23.debug.sam")
    got = bam_to_consensus(sam, realign=True, backend="jax")
    want = bam_to_consensus(sam, realign=True, backend="numpy")
    _assert_products_equal(got, want)


def test_sharded_direct_small_ref():
    """Direct sharded_consensus on a tiny reference (L barely >= devices):
    blocks are minimal and mostly padding."""
    bam = require_data("data_minimap2", "1.1.multi.bam")
    ev = _events(bam)
    mesh = make_mesh()
    for rid in ev.present_ref_ids:
        from kindel_tpu.call import call_consensus
        from kindel_tpu.pileup import build_pileup

        res, dmin, dmax, _ = sharded_consensus(ev, rid, mesh)
        want = call_consensus(build_pileup(ev, rid))
        assert res.sequence == want.sequence
        assert res.changes == want.changes


def test_sharded_mask_ends_zero_disables_realign_regions():
    """mask_ends=0 masks every position (reference kindel.py:168 quirk) —
    the sharded realign path must produce no patches."""
    bam = require_data("data_bwa_mem", "1.1.sub_test.bam")
    ev = _events(bam)
    rid = ev.present_ref_ids[0]
    mesh = make_mesh()
    sr = ShardedRef(ev, rid, mesh, realign=True)
    assert sr.cdr_patches(0.1, 0, 7) == []
