"""kindel_tpu.aot — AOT executable export/load, fallback, GC, and the
zero-compile replica-start acceptance property.

The XLA:CPU PjRt client cannot reload serialized executables across
processes on this jaxlib (observed "Symbols not found"), which makes
the CPU suite the natural fixture for the FALLBACK half of the design:
every load failure must warn once, fall back to plain JIT, and produce
byte-identical output. The LOAD half (a real TPU replica starting with
zero compiles) is pinned by stubbing only the (de)serialization
boundary — jax's own tested API — while everything else (store keying,
index, warmup, dispatch-site registry consultation, the serve stack)
runs for real.
"""

import warnings
from pathlib import Path

import numpy as np
import pytest

from kindel_tpu import aot, tune
from kindel_tpu.batch import (
    BatchOptions,
    cohort_pad_shapes,
    launch_cohort_kernel,
    pack_cohort,
)
from kindel_tpu.serve.warmup import _SYNTH_SAM, decode_payload


@pytest.fixture(autouse=True)
def _isolated_store(tmp_path, monkeypatch):
    """Every test gets its own tune/AOT store and a clean registry."""
    monkeypatch.setenv(
        "KINDEL_TPU_TUNE_CACHE", str(tmp_path / "tune.json")
    )
    aot.clear_registry()
    yield
    aot.clear_registry()


def _warm_flush(opts=None, n_rows: int = 8):
    """One packed synthetic-lane flush (the smallest serve lane)."""
    opts = opts or BatchOptions()
    units = decode_payload(_SYNTH_SAM, opts)
    shapes = cohort_pad_shapes(units, opts)
    arrays, meta = pack_cohort(units, opts, n_rows=n_rows, shapes=shapes)
    return units, arrays, meta, opts


def _jit_wire(arrays, meta, opts):
    """The jit-path oracle for one flush (registry bypassed)."""
    from kindel_tpu.call_jax import batched_call_kernel

    args = aot.cohort_args(arrays, opts)
    return np.asarray(
        batched_call_kernel(
            *args, length=meta[0], want_masks=opts.want_masks
        )
    )


# ----------------------------------------------------------------- export


def test_export_registers_and_dispatch_is_byte_identical():
    _units, arrays, meta, opts = _warm_flush()
    want = _jit_wire(arrays, meta, opts)
    assert aot.export_cohort(arrays, meta, opts), "export did not persist"
    # the dispatch site must now serve from the registry…
    before = int(aot.counters().dispatches.value)
    out, _ = launch_cohort_kernel(arrays, meta, opts)
    assert int(aot.counters().dispatches.value) == before + 1
    # …and byte-identically to the jit path
    assert np.array_equal(np.asarray(out), want)
    # the store holds exactly one indexed blob for this signature
    entries = {
        k: v for k, v in tune.load_store().items()
        if k.startswith(aot.INDEX_PREFIX)
    }
    assert len(entries) == 1
    (entry,) = entries.values()
    blob = aot.blob_dir() / entry["blob"]
    assert blob.is_file() and blob.stat().st_size == entry["bytes"]


def test_store_disabled_is_clean_noop(monkeypatch):
    monkeypatch.setenv("KINDEL_TPU_TUNE_CACHE", "off")
    _units, arrays, meta, opts = _warm_flush()
    assert not aot.enabled()
    assert aot.provenance() == {
        "loaded": 0, "compiled": 0, "source": "disabled",
    }
    # dispatch works exactly as before AOT existed
    out, _ = launch_cohort_kernel(arrays, meta, opts)
    assert np.asarray(out).shape[0] == 8


# --------------------------------------------------------------- fallback


def test_corrupt_blob_warns_once_and_falls_back():
    _units, arrays, meta, opts = _warm_flush()
    want = _jit_wire(arrays, meta, opts)
    assert aot.export_cohort(arrays, meta, opts)
    # corrupt the blob on disk, then forget the in-process executable
    (entry,) = (
        v for k, v in tune.load_store().items()
        if k.startswith(aot.INDEX_PREFIX)
    )
    blob = aot.blob_dir() / entry["blob"]
    blob.write_bytes(b"\x00garbage" * 64)
    aot.clear_registry()

    fail_before = int(aot.counters().load_failures.value)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert aot.load_cohort(arrays, meta, opts) is None
        assert aot.load_cohort(arrays, meta, opts) is None  # cached fail
    msgs = [str(x.message) for x in w if "aot" in str(x.message)]
    assert len(msgs) == 1, f"expected ONE aot warning, got {msgs}"
    assert int(aot.counters().load_failures.value) == fail_before + 1
    # the dispatch site still serves, byte-identically, via JIT
    out, _ = launch_cohort_kernel(arrays, meta, opts)
    assert np.array_equal(np.asarray(out), want)
    assert aot.provenance()["source"] == "fresh"


def test_truncated_blob_detected_by_size_check():
    _units, arrays, meta, opts = _warm_flush()
    assert aot.export_cohort(arrays, meta, opts)
    (entry,) = (
        v for k, v in tune.load_store().items()
        if k.startswith(aot.INDEX_PREFIX)
    )
    blob = aot.blob_dir() / entry["blob"]
    blob.write_bytes(blob.read_bytes()[: entry["bytes"] // 2])
    aot.clear_registry()
    with warnings.catch_warnings(record=True):
        warnings.simplefilter("always")
        assert aot.load_cohort(arrays, meta, opts) is None
    out, _ = launch_cohort_kernel(arrays, meta, opts)
    assert np.asarray(out).shape[0] == 8  # served by JIT, no crash


def test_jaxlib_version_mismatch_is_clean_miss():
    """An entry recorded under a different jaxlib must be ignored
    without even touching the blob — version skew is a MISS, not an
    error path."""
    _units, arrays, meta, opts = _warm_flush()
    assert aot.export_cohort(arrays, meta, opts)
    (key,) = (
        k for k in tune.load_store() if k.startswith(aot.INDEX_PREFIX)
    )
    tune.record(key, {"jaxlib": "0.0.0-foreign"})
    aot.clear_registry()
    fail_before = int(aot.counters().load_failures.value)
    assert aot.load_cohort(arrays, meta, opts) is None
    # a mismatch is not a load FAILURE (nothing was deserialized)
    assert int(aot.counters().load_failures.value) == fail_before
    out, _ = launch_cohort_kernel(arrays, meta, opts)
    assert np.asarray(out).shape[0] == 8


def test_broken_registry_executable_never_serves_wrong_results():
    """A registered executable that rejects its dispatch (aval drift,
    dead device) must be invalidated and the flush re-run on JIT —
    identical bytes, one warning, no crash."""
    _units, arrays, meta, opts = _warm_flush()
    want = _jit_wire(arrays, meta, opts)
    sig = aot.cohort_sig_for(arrays, meta[0], opts)

    class _Broken:
        def __call__(self, *a):
            raise TypeError("Argument types differ from compiled types")

    aot.register(sig, _Broken())
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        out, _ = launch_cohort_kernel(arrays, meta, opts)
    assert np.array_equal(np.asarray(out), want)
    assert any("rejected a dispatch" in str(x.message) for x in w)
    assert aot.lookup(sig) is None, "broken executable must be evicted"
    # and it stays evicted: the next flush goes straight to JIT
    out2, _ = launch_cohort_kernel(arrays, meta, opts)
    assert np.array_equal(np.asarray(out2), want)


def test_real_roundtrip_loads_or_falls_back_gracefully():
    """The unstubbed serialize→deserialize path: on a backend whose
    PjRt client supports executable reload (TPU; some CPU builds) the
    loaded executable must be byte-identical to JIT — on one that does
    not (this CPU jaxlib) the load must be a warned, counted fallback.
    Either branch is a pass; crashing or diverging is the only fail."""
    _units, arrays, meta, opts = _warm_flush()
    want = _jit_wire(arrays, meta, opts)
    assert aot.export_cohort(arrays, meta, opts)
    aot.clear_registry()
    with warnings.catch_warnings(record=True):
        warnings.simplefilter("always")
        loaded = aot.load_cohort(arrays, meta, opts)
    if loaded is not None:
        got = loaded(*aot.cohort_args(arrays, opts))
        assert np.array_equal(np.asarray(got), want)
        assert aot.provenance()["source"] == "store"
    else:
        assert aot.provenance()["source"] == "fresh"
    out, _ = launch_cohort_kernel(arrays, meta, opts)
    assert np.array_equal(np.asarray(out), want)


# --------------------------------------------------------------------- GC


def test_gc_evicts_runtime_mismatched_entries_and_orphans():
    _units, arrays, meta, opts = _warm_flush()
    assert aot.export_cohort(arrays, meta, opts)
    (key,) = (
        k for k in tune.load_store() if k.startswith(aot.INDEX_PREFIX)
    )
    tune.record(key, {"device_kind": "TPU_v9_imaginary"})
    (aot.blob_dir() / "orphan.exe").write_bytes(b"stray")
    stats = aot.gc_store()
    assert stats["evicted"] == 1 and stats["kept"] == 0
    assert not list(aot.blob_dir().glob("*.exe")), "blobs must be gone"
    assert not any(
        k.startswith(aot.INDEX_PREFIX) for k in tune.load_store()
    )
    # the non-AOT half of the tune store must survive the GC untouched
    tune.record("slabs|test", {"n_slabs": 4})
    aot.gc_store()
    assert tune.lookup("slabs|test")["n_slabs"] == 4


def test_gc_bounds_total_bytes_oldest_first():
    _u, arrays8, meta8, opts = _warm_flush(n_rows=8)
    assert aot.export_cohort(arrays8, meta8, opts)
    _u, arrays16, meta16, _o = _warm_flush(n_rows=16)
    assert aot.export_cohort(arrays16, meta16, opts)
    entries = {
        k: v for k, v in tune.load_store().items()
        if k.startswith(aot.INDEX_PREFIX)
    }
    assert len(entries) == 2
    total = sum(e["bytes"] for e in entries.values())
    biggest = max(e["bytes"] for e in entries.values())
    # cap below the pair but above the bigger single entry: exactly one
    # (the older) must go
    stats = aot.gc_store(cap_bytes=(total + biggest) // 2 + 1)
    assert stats["kept"] == 1 and stats["evicted"] == 1
    assert len(list(aot.blob_dir().glob("*.exe"))) == 1


# -------------------------------------------- zero-compile replica start


def _stub_serialization(monkeypatch):
    """Stub ONLY the jax (de)serialization boundary with an in-memory
    blob store, so the zero-compile property is testable on a CPU
    backend whose PjRt client cannot reload executables. Everything
    else — keying, index, blob files, warmup, registry dispatch — runs
    for real."""
    blobs: dict[bytes, object] = {}

    def fake_serialize(compiled):
        token = f"stub-blob-{len(blobs)}".encode()
        blobs[token] = compiled
        return token

    def fake_deserialize(data):
        return blobs[bytes(data)]

    monkeypatch.setattr(aot, "_serialize_compiled", fake_serialize)
    monkeypatch.setattr(aot, "_deserialize_compiled", fake_deserialize)
    return blobs


def _clear_tracked_jit_caches():
    import sys

    import kindel_tpu.call_jax as cj

    for fn in (cj.batched_call_kernel, cj.batched_realign_call_kernel,
               cj.counts_call_kernel, cj.fused_call_kernel_slab):
        fn.clear_cache()
    # the segment kernel is tracked too (obs.runtime _TRACKED_KERNELS)
    # but only compiled when a ragged/paged test ran earlier in the
    # session — clear it without forcing the import
    rk = sys.modules.get("kindel_tpu.ragged.kernel")
    if rk is not None:
        rk.ragged_call_kernel.clear_cache()


def test_zero_compile_replica_start(tmp_path, monkeypatch):
    """The acceptance property: with a warm AOT store, a fresh serve
    replica performs ZERO jit compiles through warmup AND its first
    request (pinned via the jit cache-entry counter), and the first
    response is byte-identical to the bam_to_consensus oracle."""
    from test_serve import make_sam

    from kindel_tpu.obs import runtime as obs_runtime
    from kindel_tpu.serve import ConsensusClient, ConsensusService
    from kindel_tpu.serve.warmup import warm_shapes
    from kindel_tpu.workloads import bam_to_consensus

    _stub_serialization(monkeypatch)
    sam = make_sam(tmp_path / "zero.sam", seed=21)
    want = bam_to_consensus(str(sam)).consensuses

    # -- replica 0: cold host. Warmup compiles (via the AOT surface,
    # parity-checked) and bakes the store — `kindel tune --export-aot`
    # in miniature. The bake runs under the host's resolved mesh plan
    # (DESIGN.md §23), exactly as --export-aot does, so the sharded
    # executables the serving replica dispatches are the ones baked.
    from kindel_tpu.parallel import meshexec

    baked = warm_shapes(
        BatchOptions(), payloads=[str(sam)], mesh_plan=meshexec.plan()
    )
    assert baked and all(t["source"] == "fresh" for t in baked.values())

    # -- replica 1: fresh process stand-in — empty registry, empty jit
    # caches, warm store.
    aot.clear_registry()
    _clear_tracked_jit_caches()
    assert obs_runtime.jit_cache_entries() == 0

    with ConsensusService(
        max_wait_s=0.01, warm_payloads=[str(sam)]
    ) as svc:
        assert svc.wait_warm(timeout=300), "warmup never finished"
        assert obs_runtime.jit_cache_entries() == 0, (
            "warm-store warmup must LOAD executables, not compile"
        )
        health = svc.healthz()
        assert health["status"] == "ok"
        assert health["aot"]["source"] == "store"
        assert health["aot"]["loaded"] >= 2  # synthetic + payload lane
        got = ConsensusClient(svc).consensus(str(sam), timeout=120)
        assert obs_runtime.jit_cache_entries() == 0, (
            "first request on a warm replica compiled a kernel"
        )
        snap = svc.metrics.snapshot()
    assert [(r.name, r.sequence) for r in got] == [
        (r.name, r.sequence) for r in want
    ]
    # the warmup Info metric carries the compile/execute split and the
    # store provenance per shape (satellite: attributable AOT savings)
    shapes_info = snap["kindel_serve_warmup_shape"]
    assert shapes_info and all(
        s["source"] == "store"
        and "compile_s" in s and "execute_s" in s
        for s in shapes_info
    )
    assert all(float(s["compile_s"]) == 0.0 for s in shapes_info), (
        "a store-loaded shape must not have paid any compile wall"
    )


def test_store_miss_warmup_matches_pre_aot_behavior(tmp_path):
    """On a cold store the warmup compiles exactly as before this PR:
    shapes ready, sources 'fresh', first request compiles nothing new —
    today's behavior, plus a baked store as a side effect."""
    from test_serve import make_sam

    from kindel_tpu.obs import runtime as obs_runtime
    from kindel_tpu.serve import ConsensusClient, ConsensusService
    from kindel_tpu.workloads import bam_to_consensus

    sam = make_sam(tmp_path / "miss.sam", seed=22)
    want = bam_to_consensus(str(sam)).consensuses
    with ConsensusService(
        max_wait_s=0.01, warm_payloads=[str(sam)]
    ) as svc:
        assert svc.wait_warm(timeout=300)
        assert svc.healthz()["aot"]["source"] == "fresh"
        entries_after_warm = obs_runtime.jit_cache_entries()
        got = ConsensusClient(svc).consensus(str(sam), timeout=120)
        assert obs_runtime.jit_cache_entries() == entries_after_warm, (
            "first post-warmup request compiled a new kernel shape"
        )
    assert [(r.name, r.sequence) for r in got] == [
        (r.name, r.sequence) for r in want
    ]
    # the miss-path warmup baked the store for the next replica
    assert any(
        k.startswith(aot.INDEX_PREFIX) for k in tune.load_store()
    )
