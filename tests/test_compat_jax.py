"""kindel_tpu.compat jax version shims — the one place raw
`jax.shard_map` / `jax.distributed` attribute access is legal (analysis
rule jax-compat-confinement). Both spellings of every shim are covered:
the modern top-level surface and the 0.4.x fallback, each exercised
regardless of which jax is actually pinned (monkeypatched where the
real module only offers one side)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from kindel_tpu import compat


def test_shard_map_resolves_and_runs():
    """compat.shard_map is callable on the pinned jax and runs a real
    mapped program with the keyword signature every call site uses."""
    n = len(jax.devices())
    mesh = Mesh(np.asarray(jax.devices()), ("x",))
    mapped = compat.shard_map(
        lambda a: a * 2,
        mesh=mesh,
        in_specs=(P("x"),),
        out_specs=P("x"),
    )
    out = mapped(jnp.arange(n, dtype=jnp.int32))
    assert np.array_equal(np.asarray(out), np.arange(n) * 2)


def test_shard_map_spelling_matches_jax_surface():
    """Whichever spelling the pinned jax offers is the one compat
    re-exports — top-level `jax.shard_map` where it exists, else the
    0.4.x `jax.experimental.shard_map` home."""
    if hasattr(jax, "shard_map"):
        assert compat.shard_map is jax.shard_map
    else:
        from jax.experimental.shard_map import shard_map as experimental

        assert compat.shard_map is experimental


def test_axis_size_both_spellings():
    """compat.axis_size works inside a mapped body on the pinned jax
    (psum(1) fallback on 0.4.x, lax.axis_size where it exists)."""
    n = len(jax.devices())
    mesh = Mesh(np.asarray(jax.devices()), ("x",))
    mapped = compat.shard_map(
        lambda a: a + compat.axis_size("x"),
        mesh=mesh,
        in_specs=(P("x"),),
        out_specs=P("x"),
    )
    out = mapped(jnp.zeros(n, dtype=jnp.int32))
    assert np.asarray(out).tolist() == [n] * n


def test_distributed_is_initialized_modern_spelling(monkeypatch):
    """When jax.distributed.is_initialized exists, compat routes
    through it verbatim — both truth values."""
    calls = []

    def fake(value):
        def _probe():
            calls.append(value)
            return value

        return _probe

    monkeypatch.setattr(
        jax.distributed, "is_initialized", fake(True), raising=False
    )
    assert compat.distributed_is_initialized() is True
    monkeypatch.setattr(
        jax.distributed, "is_initialized", fake(False), raising=False
    )
    assert compat.distributed_is_initialized() is False
    assert calls == [True, False]


def test_distributed_is_initialized_04x_spelling(monkeypatch):
    """On jax without the public predicate (the pinned 0.4.37), compat
    reads the client handle off jax._src.distributed.global_state:
    None → no group, a live handle → group up."""
    from jax._src import distributed as distributed_src

    if hasattr(jax.distributed, "is_initialized"):
        monkeypatch.delattr(jax.distributed, "is_initialized")
    monkeypatch.setattr(
        distributed_src.global_state, "client", None, raising=False
    )
    assert compat.distributed_is_initialized() is False
    monkeypatch.setattr(
        distributed_src.global_state, "client", object(), raising=False
    )
    assert compat.distributed_is_initialized() is True


def test_initialize_distributed_uses_compat_predicate(monkeypatch):
    """parallel.distributed routes its already-initialized short-circuit
    through the compat shim — a live group (whichever spelling reports
    it) makes a second initialize() a no-op, never a crash."""
    from kindel_tpu.parallel import distributed as dist

    monkeypatch.setattr(
        dist.compat, "distributed_is_initialized", lambda: True
    )
    called = []
    monkeypatch.setattr(
        dist.compat, "distributed_initialize",
        lambda *a, **k: called.append(1),
    )
    # group "up", single process → False, and initialize untouched
    assert dist.initialize_distributed() is False
    assert not called


def test_ensure_cpu_collectives_is_idempotent_and_safe():
    """The CPU collectives enable is callable any number of times and
    never raises — including after the backend is already up (this
    process's backend initialized long ago)."""
    compat.ensure_cpu_collectives()
    compat.ensure_cpu_collectives()
