"""Hermetic accelerator-environment helpers for driver entry points.

This container reaches its TPU through a stdio relay (`/root/.relay.py`)
bridging 127.0.0.1:808x to the host orchestrator, and a sitecustomize hook
registers the PJRT plugin in *every* interpreter when PALLAS_AXON_POOL_IPS
is set. Two failure modes follow:

  1. relay dead → any ``import jax`` hangs forever (plugin retries the
     dead endpoint), including ``JAX_PLATFORMS=cpu`` runs;
  2. relay port open but backend broken → jax raises RuntimeError
     ("Unable to initialize backend 'axon'") at first device use.

Both killed round 1's driver artifacts (BENCH_r01 rc=1, MULTICHIP_r01
rc=124). The rule encoded here: driver-facing parents (bench.py,
__graft_entry__.dryrun_multichip) NEVER import jax themselves. All jax
work happens in a watchdog-timed child process; CPU children run with the
pool hook scrubbed so they cannot touch the relay at all.

Standalone stdlib-only module: importing it must never trigger the package
(kindel_tpu imports jax transitively).
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent

#: Ports the relay listens on (all or none) — see /root/.relay.py PORTS.
RELAY_PORTS = (8082, 8083, 8087)


def pool_advertised() -> bool:
    """True when this interpreter would auto-register the tunneled
    accelerator plugin (the sitecustomize hook keys on this env var)."""
    return bool(os.environ.get("PALLAS_AXON_POOL_IPS"))


def relay_alive(timeout: float = 1.0) -> bool:
    """TCP-probe the relay. Port liveness only — a listening relay whose
    backend is broken still shows alive; callers must still watchdog the
    child that actually uses jax."""
    for port in RELAY_PORTS:
        s = socket.socket()
        s.settimeout(timeout)
        try:
            s.connect(("127.0.0.1", port))
            return True
        except OSError:
            continue
        finally:
            s.close()
    return False


def wait_for_relay(max_wait: float = 30.0) -> bool:
    """Probe with backoff for up to ``max_wait`` seconds: survives the
    window where the orchestrator is (re)starting the relay. Returns
    liveness at the end of the wait."""
    deadline = time.monotonic() + max_wait
    delay = 1.0
    while True:
        if relay_alive():
            return True
        if time.monotonic() >= deadline:
            return False
        time.sleep(min(delay, max(deadline - time.monotonic(), 0.1)))
        delay = min(delay * 2, 8.0)


def scrubbed_cpu_env(n_virtual_devices: int | None = None) -> dict:
    """A child environment that cannot reach the accelerator plugin:
    pool hook disabled, JAX_PLATFORMS=cpu, optional N-device virtual CPU
    topology, repo on PYTHONPATH."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # sitecustomize no-ops without it
    env.pop("AXON_POOL_SVC_OVERRIDE", None)
    env.pop("AXON_LOOPBACK_RELAY", None)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    if n_virtual_devices is not None:
        flags.append(
            f"--xla_force_host_platform_device_count={n_virtual_devices}"
        )
    env["XLA_FLAGS"] = " ".join(flags)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    return env


def accelerator_env() -> dict:
    """A child environment that uses the tunneled accelerator.

    JAX_PLATFORMS is pinned to the plugin's platform: without it, a
    registered-but-broken backend makes jax *fall back to CPU with a
    warning*, and the child would report a CPU measurement as the
    accelerator attempt (the sitecustomize hook relies on the same
    pinning to fail loudly)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = env.get("JAX_PLATFORMS") or "axon"
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    return env


def run_child(
    argv: list[str],
    env: dict,
    timeout: float,
) -> subprocess.CompletedProcess:
    """Run a child under a hard watchdog. Never raises on timeout or
    non-zero exit; the caller inspects returncode/stdout/stderr.
    returncode is 124 on timeout (mirroring coreutils timeout)."""
    try:
        return subprocess.run(
            argv,
            env=env,
            cwd=str(REPO),
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired as e:

        def _txt(b):
            if b is None:
                return ""
            return b.decode(errors="replace") if isinstance(b, bytes) else b

        return subprocess.CompletedProcess(
            argv, 124, _txt(e.stdout), _txt(e.stderr) + "\n[watchdog timeout]"
        )


def python_child(code: str, env: dict, timeout: float):
    """`python -c code` under the watchdog."""
    return run_child([sys.executable, "-c", code], env, timeout)


#: Watchdog for the PJRT-init pre-flight. Healthy client creation over the
#: tunnel measures ~2-15 s; a wedged backend hangs in make_c_api_client
#: forever (observed 2026-07-30: ports open, client init never returns).
PJRT_PROBE_TIMEOUT_S = 90.0


def pjrt_probe(timeout: float = PJRT_PROBE_TIMEOUT_S) -> tuple[bool, str]:
    """Cheap pre-flight distinguishing relay failure mode 2b: ports accept
    TCP but the PJRT client hangs during initialization. Spawns a child
    that creates the accelerator backend and runs one tiny computation;
    returns (ok, note). Callers use it to skip a full bench watchdog burn
    (420 s) when the backend cannot even initialize (90 s)."""
    code = (
        "import jax, jax.numpy as jnp\n"
        "d = jax.devices()\n"
        "x = jnp.ones((8, 8))\n"
        "jax.block_until_ready(x + x)\n"
        "print('PJRT_OK', jax.default_backend(), len(d))\n"
    )
    proc = python_child(code, accelerator_env(), timeout)
    out = (proc.stdout or "").strip().splitlines()
    ok_line = next((ln for ln in out if ln.startswith("PJRT_OK")), None)
    if proc.returncode == 0 and ok_line and " cpu " not in f" {ok_line} ":
        return True, ok_line
    if ok_line and " cpu " in f" {ok_line} ":
        # JAX_PLATFORMS pinning lost somewhere — never let a CPU run pass
        # as (or obscure) accelerator evidence.
        return False, f"pjrt probe ran on cpu backend: {ok_line}"
    if proc.returncode == 124:
        return False, f"pjrt client init hung >{timeout:.0f}s (ports open)"
    tail = (proc.stderr or "")[-300:]
    return False, f"pjrt probe rc={proc.returncode}: {tail}"
