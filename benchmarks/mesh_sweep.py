"""Mesh sweep: one flush fanned across dp local devices, per-width.

The per-replica mesh executor (kindel_tpu.parallel.meshexec, DESIGN.md
§23) shards every dispatch tier over a dp device mesh. This scenario
replays the shape-diverse request set (`ragged_load.make_mixed_sams`)
through the serve path at each candidate width and reports, per dp:
wall time, device dispatch count, pad-slot occupancy, h2d/d2h transfer
deltas, and the jit-cache entries the width cost — with byte-identity
asserted against the dp=1 run (a sweep that silently changed the answer
would be worse than no sweep). `bench.py` attaches the report as its
`mesh` object; `MULTICHIP_r06.json` records one run.

Standalone:

    python -m benchmarks.mesh_sweep --requests 10
"""

from __future__ import annotations

import json
import sys
import tempfile
import threading
import time
from pathlib import Path

from benchmarks.ragged_load import (
    _counter_totals,
    _global_snapshot,
    make_mixed_sams,
)

#: candidate widths; each clamps to the devices actually visible
SWEEP_DPS = (1, 2, 4, 8)


def run_mesh_sweep(requests: int = 10, seed: int = 0,
                   batch_mode: str = "ragged",
                   max_wait_s: float = 0.15,
                   dps=SWEEP_DPS) -> dict:
    """Serve the mixed-shape request set once per mesh width; returns
    {"identical": ..., "batch_mode": ..., "widths": {dp: report}}."""
    import jax

    from kindel_tpu.obs import runtime as obs_runtime
    from kindel_tpu.serve import ConsensusClient, ConsensusService
    from kindel_tpu.tune import TuningConfig

    n_dev = len(jax.devices())
    widths = sorted({min(d, n_dev) for d in dps})
    tmp = tempfile.TemporaryDirectory(prefix="kindel_mesh_sweep_")
    try:
        payloads = [
            p.read_bytes()
            for p in make_mixed_sams(Path(tmp.name), requests, seed)
        ]

        def run_width(dp: int):
            snap0 = _global_snapshot()
            cache0 = obs_runtime.jit_cache_sizes()
            h2d_c, d2h_c = obs_runtime.transfer_counters()
            tr0 = (int(h2d_c.value), int(d2h_c.value))
            results: list = [None] * len(payloads)
            errors: list = []
            t0 = time.perf_counter()
            with ConsensusService(
                tuning=TuningConfig(batch_mode=batch_mode, mesh=dp),
                max_wait_s=max_wait_s, decode_workers=4,
            ) as svc:
                client = ConsensusClient(svc)

                def one(i):
                    try:
                        results[i] = client.fasta(payloads[i], timeout=600)
                    except Exception as e:  # noqa: BLE001
                        errors.append(repr(e))

                threads = [
                    threading.Thread(target=one, args=(i,))
                    for i in range(len(payloads))
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                svc_snap = svc.metrics.snapshot()
            wall = time.perf_counter() - t0
            snap1 = _global_snapshot()
            cache1 = obs_runtime.jit_cache_sizes()

            def delta(prefix):
                return _counter_totals(snap1, prefix) - _counter_totals(
                    snap0, prefix
                )

            payload = delta("kindel_dispatch_payload_bases_total")
            padded = delta("kindel_dispatch_padded_bases_total")
            report = {
                "errors": len(errors),
                "wall_s": round(wall, 3),
                "dispatches": int(
                    svc_snap.get("kindel_serve_device_dispatches_total", 0)
                ),
                "payload_bases": payload,
                "padded_bases": padded,
                "occupancy": round(payload / padded, 4) if padded else 0.0,
                "h2d_bytes": int(h2d_c.value) - tr0[0],
                "d2h_bytes": int(d2h_c.value) - tr0[1],
                "jit_cache_entries": sum(cache1.values())
                - sum(cache0.values()),
            }
            return results, report

        reports: dict = {}
        base_results = None
        identical = True
        for dp in widths:
            results, report = run_width(dp)
            reports[str(dp)] = report
            if base_results is None:
                base_results = results
            elif results != base_results:
                identical = False
        return {
            "requests": requests,
            "batch_mode": batch_mode,
            "devices": n_dev,
            "identical": identical,
            "widths": reports,
        }
    finally:
        tmp.cleanup()


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--requests", type=int, default=10)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--batch-mode", default="ragged",
                    choices=("lanes", "ragged", "paged"))
    args = ap.parse_args(argv)
    report = run_mesh_sweep(
        requests=args.requests, seed=args.seed,
        batch_mode=args.batch_mode,
    )
    json.dump(report, sys.stdout, indent=1)
    print()
    return 0 if report["identical"] else 1


if __name__ == "__main__":
    sys.exit(main())
