"""On-accelerator validation of the cohort footprint estimate.

Prints ONE JSON line comparing `batch._row_bytes` (the group-packing
budget's per-row estimate) against the device bytes XLA actually keeps
alive right after a realign group dispatch — the relay-return checklist's
last item (VERDICT r4 weak 5). The CPU-backend version of this check is
pinned as tests/test_batch.py::test_row_bytes_estimate_vs_live_buffers;
this script exists so a TPU uptime window banks the same ratio on real
HBM (run by benchmarks/relay_watch.py after a successful TPU bench).
"""

from __future__ import annotations

import gc
import json
import sys
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import jax  # noqa: E402

from kindel_tpu import batch as B  # noqa: E402

DATA = Path("/root/reference/tests/data_bwa_mem")


def measure_cohort_budget(paths) -> dict:
    """The one shared measurement: estimate vs observed live device bytes
    for a realign group dispatch. tests/test_batch.py asserts bounds on
    this dict (CPU); this script json-prints it (TPU window) — a single
    implementation so the two can never measure different quantities."""
    opts = B.BatchOptions(realign=True)
    with ThreadPoolExecutor(2) as pool:
        units = B._load_units(paths, pool, opts)
    gc.collect()
    # hold the snapshot arrays themselves alive until `fresh` is computed
    # — with only their id()s retained, a freed-then-reallocated buffer
    # could reuse an id and silently drop a fresh array from the delta
    before_arrays = jax.live_arrays()
    before = {id(a) for a in before_arrays}
    out, _meta = B._dispatch_device_call(units, opts)
    jax.block_until_ready(out)
    gc.collect()
    fresh = [a for a in jax.live_arrays() if id(a) not in before]
    actual = sum(a.nbytes for a in fresh)
    del before_arrays
    _sharding, dp = B._dp_sharding(len(units))
    rows = -(-len(units) // dp) * dp  # dummy-row padding to a dp multiple
    Lb = B._bucket(max(u.L for u in units), 1024)
    est = rows * B._row_bytes(Lb, realign=True)
    return {
        "metric": "cohort_budget_live_bytes",
        "backend": jax.default_backend(),
        "rows": rows,
        "Lb": Lb,
        "actual_bytes": int(actual),
        "estimate_bytes": int(est),
        "ratio": round(actual / est, 3) if est else None,
    }


def main() -> None:
    paths = [DATA / f"{i}.1.sub_test.bam" for i in (1, 2, 3)]
    if not all(p.exists() for p in paths):
        print(json.dumps({"error": "corpus unavailable"}))
        return
    print(json.dumps(measure_cohort_budget(paths)))


if __name__ == "__main__":
    main()
