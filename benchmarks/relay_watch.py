"""TPU relay watcher: convert any tunnel-uptime window into committed evidence.

The accelerator relay (ports 8082/8083/8087) flaps between sessions; two
rounds of BENCH artifacts were cpu-fallback because `bench.py` probes once
and gives up. This watcher runs for the whole round: every PERIOD seconds it
probes the relay ports, appends one JSON line per probe to RELAY_LOG.jsonl
(so a dead-all-round relay is *provably* environmental), and whenever the
relay is up and no TPU bench has succeeded in the last REBENCH_S seconds it
runs `bench.py` and appends the result to BENCH_ATTEMPTS.jsonl.

Usage: python benchmarks/relay_watch.py [--once]
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

import _hermetic as hz  # noqa: E402  (stdlib-only; needs REPO on sys.path)

PROBE_LOG = REPO / "RELAY_LOG.jsonl"
BENCH_LOG = REPO / "BENCH_ATTEMPTS.jsonl"
PORTS = (8082, 8083, 8087)
PERIOD = 180  # seconds between probes
REBENCH_S = 3600  # re-run bench at most hourly once a TPU result exists
FAIL_RETRY_S = 1800  # min gap between attempts that didn't yield a TPU result


def probe() -> dict[int, bool]:
    out = {}
    for port in PORTS:
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=3):
                out[port] = True
        except OSError:
            out[port] = False
    return out


def append(path: Path, obj: dict) -> None:
    with path.open("a") as fh:
        fh.write(json.dumps(obj) + "\n")


def stamp(ts: float) -> dict:
    """The shared {ts, iso} prefix of every log record in this file."""
    return {
        "ts": round(ts, 1),
        "iso": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(ts)),
    }


MICROPROF_LOG = REPO / "MICROPROF_TPU.log"


def run_microprof(ts_iso: str) -> None:
    """After a successful TPU bench, capture one per-phase attribution
    (now measuring the packed single-transfer wire) for BASELINE. Runs
    under _hermetic.accelerator_env so a broken-but-registered backend
    fails loudly instead of silently profiling the CPU; the 'device:'
    line is always kept so the log can never pass a CPU profile off as
    TPU evidence."""
    try:
        proc = subprocess.run(
            [sys.executable, str(REPO / "benchmarks" / "microprof.py")],
            capture_output=True, text=True, timeout=300, cwd=REPO,
            env=hz.accelerator_env(),
        )
        with MICROPROF_LOG.open("a") as fh:
            fh.write(f"=== {ts_iso} rc={proc.returncode}\n")
            if len(proc.stdout) > 1700:
                # long output: keep the 'device: ...' head AND the tail
                fh.write(
                    proc.stdout[:200] + "\n...\n" + proc.stdout[-1500:] + "\n"
                )
            else:
                fh.write(proc.stdout + "\n")
            if proc.returncode != 0:  # keep the traceback as evidence too
                fh.write(proc.stderr[-2000:] + "\n")
    except Exception as e:  # evidence capture must never kill the watcher
        with MICROPROF_LOG.open("a") as fh:
            fh.write(f"=== {ts_iso} microprof failed: {e}\n")


def run_budget_probe(ts: float) -> None:
    """After a successful TPU bench, bank the on-HBM cohort-budget
    validation (estimate vs live buffers — relay-return checklist item
    d). One JSON line into BENCH_ATTEMPTS.jsonl, tagged by its metric."""
    try:
        proc = subprocess.run(
            [sys.executable, str(REPO / "benchmarks" / "budget_probe.py")],
            capture_output=True, text=True, timeout=300, cwd=REPO,
            env=hz.accelerator_env(),
        )
        line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else ""
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            rec = {"error": "unparseable budget probe",
                   "stdout_tail": line[:300]}
        rec["rc"] = proc.returncode
        if proc.returncode != 0 or "error" in rec:
            # keep the traceback: this log's whole purpose is banking the
            # rare TPU-window evidence (matches run_microprof)
            rec["stderr_tail"] = proc.stderr[-500:]
        append(BENCH_LOG, {**stamp(ts), **rec})
    except Exception as e:  # evidence capture must never kill the watcher
        append(BENCH_LOG, {**stamp(ts), "error": f"budget probe: {e}"})


def run_bench() -> dict:
    t0 = time.time()
    try:
        # the watcher has just probed relay + PJRT init on its own
        # cadence — pin bench to one TPU attempt with its own pre-flight
        # suppressed, so worst case (~15 s relay wait + 560 s TPU child +
        # 300 s CPU child ≈ 875 s) stays inside this 900 s kill window
        env = dict(os.environ)
        env["KINDEL_TPU_BENCH_RELAY_WAIT_S"] = "15"
        env["KINDEL_TPU_BENCH_TPU_ATTEMPTS"] = "1"
        env["KINDEL_TPU_BENCH_SKIP_PJRT_PROBE"] = "1"
        proc = subprocess.run(
            [sys.executable, str(REPO / "bench.py")],
            capture_output=True,
            text=True,
            timeout=900,
            cwd=REPO,
            env=env,
        )
        line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else ""
        try:
            result = json.loads(line)
        except (json.JSONDecodeError, IndexError):
            result = {"error": "unparseable", "stdout_tail": line[:500]}
        result["rc"] = proc.returncode
    except subprocess.TimeoutExpired:
        result = {"error": "timeout", "rc": -1}
    result["bench_wall_s"] = round(time.time() - t0, 1)
    return result


def main() -> None:
    once = "--once" in sys.argv
    last_tpu_bench = 0.0
    last_attempt = 0.0
    # resume: find prior attempts so restarts don't immediately re-bench
    if BENCH_LOG.exists():
        for raw in BENCH_LOG.read_text().splitlines():
            try:
                rec = json.loads(raw)
            except json.JSONDecodeError:
                continue
            last_attempt = rec.get("ts", 0.0)
            if rec.get("backend") == "tpu" and rec.get("rc") == 0:
                last_tpu_bench = rec.get("ts", 0.0)
    while True:
        now = time.time()
        ports = probe()
        up = all(ports.values())
        append(
            PROBE_LOG,
            {**stamp(now), "ports": {str(k): v for k, v in ports.items()}, "relay_up": up},
        )
        # throttle: ports-up-but-cpu-fallback must not re-run the multi-
        # minute bench every probe cycle — any attempt counts for
        # FAIL_RETRY_S, a real TPU result for REBENCH_S
        if (
            up
            and now - last_tpu_bench > REBENCH_S
            and now - last_attempt > FAIL_RETRY_S
        ):
            last_attempt = now
            # Pre-flight: ports-open-but-client-hung (observed 2026-07-30)
            # would burn bench's full 420 s watchdog; a 90 s PJRT probe
            # converts that into sharp, cheap evidence in both logs. Only
            # meaningful when the pool hook is advertised — without it
            # bench.py skips its TPU loop and still yields a CPU record.
            pjrt_ok, pjrt_note = True, "pool not advertised"
            if hz.pool_advertised():
                pjrt_ok, pjrt_note = hz.pjrt_probe()
                append(
                    PROBE_LOG,
                    {
                        **stamp(time.time()),
                        "pjrt_ok": pjrt_ok,
                        "pjrt_note": pjrt_note,
                    },
                )
            if not pjrt_ok:
                append(
                    BENCH_LOG,
                    {
                        **stamp(now),
                        "skipped": "pjrt preflight failed",
                        "note": pjrt_note,
                    },
                )
                if once:
                    break
                time.sleep(PERIOD)
                continue
            result = run_bench()
            result.update(stamp(now))
            append(BENCH_LOG, result)
            if result.get("backend") == "tpu" and result.get("rc") == 0:
                last_tpu_bench = now
                # re-probe before the (up to 300 s) microprof run so the
                # uptime log has no hole exactly around the TPU-up window
                now2 = time.time()
                ports2 = probe()
                append(
                    PROBE_LOG,
                    {
                        **stamp(now2),
                        "ports": {str(k): v for k, v in ports2.items()},
                        "relay_up": all(ports2.values()),
                    },
                )
                run_microprof(result["iso"])
                run_budget_probe(time.time())
        if once:
            break
        time.sleep(PERIOD)


if __name__ == "__main__":
    main()
