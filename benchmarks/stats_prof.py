"""Bytes-over-wire accounting for the stats workloads (VERDICT r4 item 3).

Runs `weights --backend jax` (the heaviest stats table) twice on the same
BAM — compact nonzero-rows u16 wire vs dense int32 download — and prints
each run's measured d2h bytes (kindel_tpu.utils.wirestats) and wall time,
plus the parity check. On the tunneled TPU the byte ratio is the expected
end-to-end win; on CPU the bytes still prove the wire contract.

Usage: python benchmarks/stats_prof.py [bam_path]
"""

import os
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def main() -> None:
    bam = Path(
        sys.argv[1]
        if len(sys.argv) > 1
        else "/root/reference/tests/data_minimap2_bact/bact.tiny.bam"
    )
    import jax

    from kindel_tpu import workloads
    from kindel_tpu.utils import wirestats

    print(f"device: {jax.devices()[0]}  bam: {bam.name}", flush=True)

    # untimed warm-up so the first timed mode doesn't absorb the shared
    # scatter-kernel jit compiles (byte counters are reset afterwards)
    workloads.weights(bam, backend="jax")

    outputs = {}
    for mode in ("dense", "compact"):
        if mode == "compact":
            os.environ["KINDEL_TPU_COMPACT_STATS"] = "1"  # even on CPU
            os.environ.pop("KINDEL_TPU_DENSE_STATS", None)
        else:
            os.environ["KINDEL_TPU_DENSE_STATS"] = "1"
            os.environ.pop("KINDEL_TPU_COMPACT_STATS", None)
        wirestats.reset()
        t0 = time.perf_counter()
        df = workloads.weights(bam, backend="jax")
        wall = time.perf_counter() - t0
        snap = wirestats.snapshot()
        outputs[mode] = df
        print(
            f"{mode}: d2h={snap['d2h_bytes']/1e6:.2f} MB in "
            f"{snap['d2h_fetches']} fetches, wall={wall:.2f}s, "
            f"rows={len(df)}",
            flush=True,
        )
    os.environ.pop("KINDEL_TPU_DENSE_STATS", None)
    os.environ.pop("KINDEL_TPU_COMPACT_STATS", None)
    same = outputs["dense"].equals(outputs["compact"])
    print(f"parity: {'identical' if same else 'DIVERGED'}", flush=True)
    if not same:
        sys.exit(1)


if __name__ == "__main__":
    main()
