"""Open-loop load generator for the streaming consensus lane.

S concurrent sessions each receive A appended read batches at a fixed
arrival interval — OPEN loop: the appender never waits for the
previous append's ack before sending the next, so backpressure shows
up as deferred acks and update latency, not as a slowed generator
(the serving-lane complement of benchmarks/paged_load.py). Optionally
the service is stopped and respawned mid-stream over its durable
journal, so the report's replay count measures a real recovery, not a
counter at rest.

Reported per run: client-observed update latency p50/p99 (append
submit → emission-decision ack for the gate-crossing appends),
emits-per-append (how many appends actually moved the called bases),
d2h bytes per published update (the device emit path's O(consensus)
readback), suppressed snapshots, replay count, and the final-FASTA
digest with a `converged` bit against the one-shot oracle over each
session's concatenated batches — the lane's byte-identity contract,
asserted on every bench round.

Wired into bench.py's optional-metrics path: the `stream` object
(KINDEL_TPU_BENCH_STREAM=1 opt-in off-CPU). Standalone:

    python -m benchmarks.stream_load --sessions 4 --appends 6
"""

from __future__ import annotations

import hashlib
import json
import sys
import tempfile
import threading
import time
from pathlib import Path


def _synth_sam(dest: Path, ref_len: int = 1024, n_reads: int = 40,
               seed: int = 0) -> Path:
    """One appended read batch: small enough that the emission gate —
    not decode — dominates the measured path."""
    import numpy as np

    rng = np.random.default_rng(seed)
    lines = ["@HD\tVN:1.6", f"@SQ\tSN:stream1\tLN:{ref_len}"]
    for i in range(n_reads):
        pos = int(rng.integers(0, ref_len - 80))
        seq = "".join("ACGT"[b] for b in rng.integers(0, 4, size=80))
        cigar = ("40M2D38M2S", "80M", "38M4I38M")[i % 3]
        lines.append(
            f"r{i}\t0\tstream1\t{pos + 1}\t60\t{cigar}\t*\t0\t0\t{seq}\t*"
        )
    dest.write_text("\n".join(lines) + "\n")
    return dest


def _concat_sam(dest: Path, parts) -> Path:
    lines = []
    for i, p in enumerate(parts):
        for ln in p.read_text().splitlines():
            if ln.startswith("@") and i > 0:
                continue
            lines.append(ln)
    dest.write_text("\n".join(lines) + "\n")
    return dest


def run_stream_load(sessions: int = 4, appends_per_session: int = 6,
                    interval_s: float = 0.01, emit_delta: int = 1,
                    batch_reads: int = 40, max_wait_s: float = 0.01,
                    respawn: bool = True, **service_kwargs) -> dict:
    """Run the open loop; returns a JSON-able report dict.

    `respawn=True` stops the service after the first half of the
    appends and restarts it over the same journal directory (shared
    metrics registry, so counters span both lives): the journal's
    OPEN/APPEND frames replay every session under its original id and
    the second half of the load lands on the respawned lease — the
    report's `replays` then counts real recoveries."""
    from kindel_tpu.io.fasta import format_fasta
    from kindel_tpu.obs.metrics import MetricsRegistry
    from kindel_tpu.serve import ConsensusService
    from kindel_tpu.workloads import bam_to_consensus

    tmp = tempfile.TemporaryDirectory(prefix="kindel_stream_load_")
    root = Path(tmp.name)
    batches = {
        s: [
            _synth_sam(
                root / f"s{s}_b{k}.sam", n_reads=batch_reads,
                seed=1000 + 100 * s + k,
            )
            for k in range(appends_per_session + 1)
        ]
        for s in range(sessions)
    }
    registry = MetricsRegistry()
    journal_dir = str(root / "journal") if respawn else None

    def make_service():
        return ConsensusService(
            max_wait_s=max_wait_s, emit_delta=emit_delta,
            journal_dir=journal_dir, metrics=registry,
            **service_kwargs,
        ).start()

    lat_lock = threading.Lock()
    update_lat: list[float] = []
    deferred = [0]
    errors: list[str] = []

    def track(fut, t0: float):
        def _done(f):
            dt = time.perf_counter() - t0
            try:
                ack = f.result()
            except Exception as e:  # noqa: BLE001 — typed retire at respawn
                with lat_lock:
                    errors.append(repr(e))
                return
            with lat_lock:
                if ack.get("emitted"):
                    update_lat.append(dt)
                else:
                    deferred[0] += 1
        fut.add_done_callback(_done)

    def append_phase(svc, ks):
        """One open-loop pass: every session gets its batch `k` for
        each k in `ks`, issued on the interval clock, acks tracked
        asynchronously."""
        futs = []
        for k in ks:
            for s in range(sessions):
                t0 = time.perf_counter()
                try:
                    fut = svc.sessions.append(
                        sids[s], batches[s][k].read_bytes()
                    )
                except Exception as e:  # noqa: BLE001 — shed at admission
                    with lat_lock:
                        errors.append(repr(e))
                    continue
                track(fut, t0)
                futs.append(fut)
                time.sleep(interval_s)
        return futs

    def _wait(pred, timeout=120.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if pred():
                return True
            time.sleep(0.02)
        return False

    t_start = time.perf_counter()
    svc = make_service()
    try:
        sids = {
            s: svc.sessions.open(batches[s][0].read_bytes())
            for s in range(sessions)
        }
        half = max(1, appends_per_session // 2)
        futs = append_phase(svc, range(1, 1 + half))

        if respawn:
            # mid-stream crash-and-respawn: the journal carries every
            # admitted append across the gap (WAL-then-merge)
            for f in futs:
                f.cancel()  # no-op on settled; typed retire covers rest
            svc.stop()
            svc = make_service()
            assert _wait(lambda: registry.snapshot().get(
                "kindel_stream_replays_total", 0
            ) >= sessions), "journal replay did not restore the sessions"

        futs = append_phase(
            svc, range(1 + half, 1 + appends_per_session)
        )
        for f in futs:
            try:
                f.result(timeout=300)
            except Exception:  # noqa: BLE001 — already counted by track
                pass

        finals = {
            s: svc.sessions.close(sids[s]).result(timeout=300)
            for s in range(sessions)
        }
        snap = registry.snapshot()
        wall = time.perf_counter() - t_start

        # byte-identity against the one-shot oracle: the lane's
        # contract, asserted on every bench round (a benchmark of a
        # wrong answer is not a benchmark)
        converged = True
        fastas = []
        for s in range(sessions):
            cat = _concat_sam(root / f"s{s}_oracle.sam", batches[s])
            want = format_fasta(bam_to_consensus(str(cat)).consensuses)
            fastas.append(finals[s]["fasta"])
            converged = converged and finals[s]["fasta"] == want
    finally:
        svc.stop()
        tmp.cleanup()

    update_lat.sort()

    def pct(q: float) -> float:
        if not update_lat:
            return 0.0
        return update_lat[min(len(update_lat) - 1,
                              int(q * len(update_lat)))]

    appends = int(snap.get("kindel_stream_appends_total", 0))
    emits = int(snap.get("kindel_stream_emits_total", 0))
    emit_bytes = int(snap.get("kindel_stream_emit_bytes_total", 0))
    return {
        "sessions": sessions,
        "appends_per_session": appends_per_session,
        "appends": appends,
        "emits": emits,
        "suppressed": int(
            snap.get("kindel_stream_suppressed_total", 0)
        ),
        "deferred_acks": deferred[0],
        "errors": len(errors),
        "wall_s": round(wall, 3),
        "update_latency_p50_s": round(pct(0.50), 4),
        "update_latency_p99_s": round(pct(0.99), 4),
        "emits_per_append": round(emits / max(appends, 1), 3),
        "d2h_bytes_per_update": round(
            emit_bytes / max(emits, 1), 1
        ),
        "replays": int(snap.get("kindel_stream_replays_total", 0)),
        "sse_events": int(
            snap.get("kindel_stream_sse_events_total", 0)
        ),
        "converged": converged,
        "fasta_distinct": len(set(fastas)),
        "fasta_sha256": hashlib.sha256(
            "\n".join(sorted(set(fastas))).encode()
        ).hexdigest(),
    }


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="open-loop streaming-consensus load generator"
    )
    ap.add_argument("--sessions", type=int, default=4)
    ap.add_argument("--appends", type=int, default=6,
                    help="appended batches per session")
    ap.add_argument("--interval-ms", type=float, default=10.0,
                    help="open-loop arrival interval per append")
    ap.add_argument("--emit-delta", type=int, default=1)
    ap.add_argument("--no-respawn", action="store_true",
                    help="skip the mid-stream journal respawn cycle")
    args = ap.parse_args(argv)
    report = run_stream_load(
        sessions=args.sessions, appends_per_session=args.appends,
        interval_s=args.interval_ms / 1000.0,
        emit_delta=args.emit_delta, respawn=not args.no_respawn,
    )
    json.dump(report, sys.stdout, indent=2)
    print()
    return 0 if report["converged"] and not report["errors"] else 1


if __name__ == "__main__":
    sys.exit(main())
