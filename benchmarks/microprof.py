"""Phase-level micro-profile of the fused consensus path on the current
JAX default device (TPU when the tunnel is up, CPU otherwise).

Usage: python benchmarks/microprof.py [bam_path]

Breaks the benchmark pipeline into decode / extract / unit-build / upload /
device-compute / download / host-assemble and prints a per-phase table,
three trials. This is the tool for attributing wall time between the
tunnel wire (upload+download), the XLA program, and host work — see
BASELINE.md for the end-to-end target.
"""

import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import numpy as np


def main() -> None:
    bam = Path(
        sys.argv[1]
        if len(sys.argv) > 1
        else "/root/reference/tests/data_minimap2_bact/bact.tiny.bam"
    )
    import jax

    from kindel_tpu.call import _insertion_calls, assemble
    from kindel_tpu.call_jax import (
        CallUnit,
        covered_index,
        decode_compact,
        fused_call_kernel_packed,
        pack_kernel_args,
        unpack_wire,
    )
    from kindel_tpu.events import extract_events
    from kindel_tpu.io import load_alignment
    from kindel_tpu.pileup import build_insertion_table
    from kindel_tpu.call_jax import _compact_bucket

    print(f"device: {jax.devices()[0]}", flush=True)

    batch = load_alignment(bam)
    ev = extract_events(batch)
    rid = ev.present_ref_ids[0]

    # warmup / compile
    u = CallUnit(ev, rid)
    up, (o_pad, b_pad, nn_pad, d_pad, i_pad) = pack_kernel_args(u)
    cov = covered_index(u.op_r_start, u.op_lens())
    c_pad = _compact_bucket(len(cov))
    buf = jax.device_put(up)
    jax.block_until_ready(buf)
    out = fused_call_kernel_packed(
        buf, o_pad=o_pad, b_pad=b_pad, nn_pad=nn_pad, d_pad=d_pad,
        i_pad=i_pad,
        length=u.L, want_masks=False, c_pad=c_pad,
    )
    jax.block_until_ready(out)
    print(
        f"wire: up={up.nbytes}B down={out.nbytes}B covered={len(cov)}"
        f"/{u.L} (compact c_pad={c_pad})",
        flush=True,
    )

    for trial in range(3):
        t0 = time.perf_counter()
        batch = load_alignment(bam)
        t1 = time.perf_counter()
        ev = extract_events(batch)
        t2 = time.perf_counter()
        u = CallUnit(ev, rid)
        cov = covered_index(u.op_r_start, u.op_lens())
        c_pad = _compact_bucket(len(cov))
        t3 = time.perf_counter()
        up, (o_pad, b_pad, nn_pad, d_pad, i_pad) = pack_kernel_args(u)
        buf = jax.device_put(up)  # ONE h2d transfer (round-3 packing)
        jax.block_until_ready(buf)
        t4 = time.perf_counter()
        out = fused_call_kernel_packed(
            buf, o_pad=o_pad, b_pad=b_pad, nn_pad=nn_pad, d_pad=d_pad,
        i_pad=i_pad,
            length=u.L, want_masks=False, c_pad=c_pad,
        )
        jax.block_until_ready(out)
        t5 = time.perf_counter()
        # ONE packed buffer, one d2h transfer (round-3 wire packing)
        plane, parts, _dmin, _dmax = unpack_wire(
            np.asarray(out), u.L, d_pad, i_pad, want_masks=False,
            c_pad=c_pad,
        )
        exc_bits, del_bits, ins_bits = parts
        t6 = time.perf_counter()
        masks = decode_compact(
            plane, exc_bits, del_bits, ins_bits, u.L, cov, u.del_pos,
            u.ins_pos,
        )
        # match the production path: resolve insertion strings when any emit
        ins_calls = (
            _insertion_calls(build_insertion_table(ev, rid))
            if masks.ins_mask.any()
            else {}
        )
        res = assemble(masks, ins_calls, None, False, 1, False, False)
        t7 = time.perf_counter()
        assert len(res.sequence) > 0
        print(
            f"trial{trial}: decode={t1-t0:.3f} extract={t2-t1:.3f} "
            f"unit={t3-t2:.3f} upload={t4-t3:.3f} compute={t5-t4:.3f} "
            f"download={t6-t5:.3f} assemble={t7-t6:.3f} "
            f"total={t7-t0:.3f}",
            flush=True,
        )

    # --- dispatch-latency probe: how much of "compute" is per-dispatch
    # relay/PJRT overhead rather than XLA program time? A trivial kernel's
    # round trip is almost pure overhead; the fused kernel's true device
    # time is roughly compute_phase - this.
    import jax.numpy as jnp

    @jax.jit
    def _tiny(x):
        return x * 2 + 1

    t = jnp.ones(128, jnp.int32)
    jax.block_until_ready(_tiny(t))  # compile
    lat = []
    for _ in range(5):
        a = time.perf_counter()
        jax.block_until_ready(_tiny(t))
        lat.append(time.perf_counter() - a)
    lat.sort()
    print(
        f"dispatch-latency: median={lat[2]*1e3:.1f}ms "
        f"min={lat[0]*1e3:.1f}ms max={lat[-1]*1e3:.1f}ms", flush=True,
    )

    # --- slab pipeline A/B (KINDEL_TPU_SLABS): consensus-call wall only
    # (decode/extract are config-independent). The watcher banks this log
    # from TPU sessions; the best config becomes the device default.
    import os

    from kindel_tpu.call_jax import call_consensus_fused

    prev_slabs = os.environ.get("KINDEL_TPU_SLABS")
    seen_effective = set()
    for n in (1, 2, 4, 8):
        # report the EFFECTIVE count after the small-contig clamp — on a
        # sub-128k reference every config collapses to 1 and printing the
        # requested values would pass timing noise off as an A/B result
        eff = max(1, min(n, int(ev.ref_lens[rid]) // 65536))
        if eff in seen_effective:
            continue
        seen_effective.add(eff)
        os.environ["KINDEL_TPU_SLABS"] = str(n)
        walls = []
        for _ in range(3):
            a = time.perf_counter()
            res, _dm, _dx = call_consensus_fused(ev, rid, build_changes=False)
            walls.append(time.perf_counter() - a)
        walls.sort()
        print(
            f"slabs={eff}: call-wall median={walls[1]:.3f}s "
            f"min={walls[0]:.3f}s (3 trials, first includes compile)",
            flush=True,
        )
    if prev_slabs is None:
        os.environ.pop("KINDEL_TPU_SLABS", None)
    else:
        os.environ["KINDEL_TPU_SLABS"] = prev_slabs


if __name__ == "__main__":
    main()
