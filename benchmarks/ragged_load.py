"""Shape-diverse serve scenario: ragged superbatching vs shape-keyed lanes.

The headline bench's serve load (`serve_load.py`) replays ONE payload —
exactly the regime the shape-keyed micro-batcher is best at, and exactly
what production traffic is not. This scenario generates the ROADMAP's
multi-sample regime instead: many small contigs, mixed reference and
read lengths, some multi-reference (metagenomic-style) payloads — and
runs the identical request set through BOTH batch modes, reporting for
each: pad-slot occupancy (payload/padded bases), pad waste, superbatch
and dispatch counts, and the jit-cache entries the load cost. `bench.py`
attaches the report as its `ragged` object; byte-identity between modes
is asserted on every run (a perf scenario that silently changed the
answer would be worse than no scenario).

Standalone:

    python -m benchmarks.ragged_load --requests 12
"""

from __future__ import annotations

import json
import sys
import tempfile
import threading
from pathlib import Path


def make_mixed_sams(out_dir: Path, n: int = 12, seed: int = 0) -> list:
    """Shape-diverse synthetic payloads: reference lengths spread over
    ~2 decades, varied read lengths/coverage, every third payload
    multi-reference (2-3 contigs — the metagenomic cohort shape)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    paths = []
    for i in range(n):
        n_refs = 1 if i % 3 else int(rng.integers(2, 4))
        lines = ["@HD\tVN:1.6"]
        specs = []
        for r in range(n_refs):
            L = int(rng.integers(256, 6000))
            specs.append((f"q{i}r{r}", L))
            lines.append(f"@SQ\tSN:q{i}r{r}\tLN:{L}")
        for ref, L in specs:
            read_len = int(rng.integers(40, 120))
            n_reads = int(rng.integers(10, 60))
            for j in range(n_reads):
                pos = int(rng.integers(0, max(1, L - read_len)))
                seq = "".join(
                    "ACGT"[b] for b in rng.integers(0, 4, size=read_len)
                )
                half = read_len // 2
                cigar = (
                    f"{read_len}M",
                    f"{half}M2D{read_len - half}M",
                    f"{half}M2I{read_len - half - 2}M",
                )[j % 3]
                lines.append(
                    f"{ref}.{j}\t0\t{ref}\t{pos + 1}\t60\t{cigar}"
                    f"\t*\t0\t0\t{seq}\t*"
                )
        p = out_dir / f"mix{i}.sam"
        p.write_text("\n".join(lines) + "\n")
        paths.append(p)
    return paths


def _counter_totals(snapshot: dict, prefix: str) -> int:
    return sum(
        int(v) for k, v in snapshot.items()
        if (k == prefix or k.startswith(prefix + "{"))
        and not isinstance(v, dict)
    )


def _global_snapshot() -> dict:
    from kindel_tpu.obs.metrics import default_registry

    return default_registry().snapshot()


def run_shape_diverse(requests: int = 12, seed: int = 0,
                      max_wait_s: float = 0.15) -> dict:
    """Run the mixed-shape request set through lanes then ragged mode;
    returns the comparison report (see module docstring)."""
    from kindel_tpu.obs import runtime as obs_runtime
    from kindel_tpu.serve import ConsensusClient, ConsensusService
    from kindel_tpu.tune import TuningConfig

    tmp = tempfile.TemporaryDirectory(prefix="kindel_ragged_load_")
    try:
        payloads = [
            p.read_bytes()
            for p in make_mixed_sams(Path(tmp.name), requests, seed)
        ]

        def run_mode(mode: str):
            snap0 = _global_snapshot()
            cache0 = obs_runtime.jit_cache_sizes()
            results: list = [None] * len(payloads)
            errors: list = []
            with ConsensusService(
                tuning=TuningConfig(batch_mode=mode),
                max_wait_s=max_wait_s, decode_workers=4,
            ) as svc:
                client = ConsensusClient(svc)

                def one(i):
                    try:
                        results[i] = client.fasta(payloads[i], timeout=600)
                    except Exception as e:  # noqa: BLE001
                        errors.append(repr(e))

                threads = [
                    threading.Thread(target=one, args=(i,))
                    for i in range(len(payloads))
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                svc_snap = svc.metrics.snapshot()
            snap1 = _global_snapshot()
            cache1 = obs_runtime.jit_cache_sizes()

            def delta(prefix):
                return _counter_totals(snap1, prefix) - _counter_totals(
                    snap0, prefix
                )

            payload = delta("kindel_dispatch_payload_bases_total")
            padded = delta("kindel_dispatch_padded_bases_total")
            report = {
                "errors": len(errors),
                "dispatches": int(
                    svc_snap.get("kindel_serve_device_dispatches_total", 0)
                ),
                "superbatches": delta("kindel_ragged_superbatches_total"),
                "lane_fallbacks": delta("kindel_ragged_fallback_total"),
                "payload_bases": payload,
                "padded_bases": padded,
                "occupancy": round(payload / padded, 4) if padded else 0.0,
                "pad_waste_bases": padded - payload,
                "jit_cache_entries": sum(cache1.values())
                - sum(cache0.values()),
            }
            return results, report

        lanes_results, lanes = run_mode("lanes")
        ragged_results, ragged = run_mode("ragged")
        return {
            "requests": requests,
            "identical": lanes_results == ragged_results,
            "lanes": lanes,
            "ragged": ragged,
        }
    finally:
        tmp.cleanup()


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=12)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    report = run_shape_diverse(requests=args.requests, seed=args.seed)
    print(json.dumps(report))
    return 0 if report["identical"] else 1


if __name__ == "__main__":
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    sys.exit(main())
