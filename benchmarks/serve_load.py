"""Closed-loop load generator for the online consensus service.

K client threads each issue M synchronous requests against an
in-process ConsensusService (no HTTP in the measured loop — this
benchmarks the queue→batcher→worker pipeline, not socket overhead) and
report throughput, client-observed p50/p99 latency, and the batch
occupancy the micro-batcher achieved. Occupancy is the number the rest
of the repo's perf story hangs on: >1 means independent requests are
riding shared device dispatches, i.e. the cohort kernel's host↔device
amortization is materializing *online*, not just for pre-assembled
cohorts.

Wired into bench.py's optional-metrics path: KINDEL_TPU_BENCH_SERVE=1
attaches this report to the round's JSON line. Standalone:

    python -m benchmarks.serve_load --clients 8 --requests 16
"""

from __future__ import annotations

import json
import sys
import tempfile
import threading
import time
from pathlib import Path


def _synth_sam(dest: Path, ref_len: int = 2048, n_reads: int = 200,
               seed: int = 0) -> Path:
    """Small synthetic workload: per-request cost stays in the regime
    where batching (not raw decode) dominates, which is the serving
    property under measurement."""
    import numpy as np

    rng = np.random.default_rng(seed)
    lines = ["@HD\tVN:1.6", f"@SQ\tSN:load1\tLN:{ref_len}"]
    for i in range(n_reads):
        pos = int(rng.integers(0, ref_len - 80))
        seq = "".join("ACGT"[b] for b in rng.integers(0, 4, size=80))
        cigar = ("40M2D38M2S", "80M", "38M4I38M")[i % 3]
        lines.append(
            f"r{i}\t0\tload1\t{pos + 1}\t60\t{cigar}\t*\t0\t0\t{seq}\t*"
        )
    dest.write_text("\n".join(lines) + "\n")
    return dest


def run_load(bam_path=None, clients: int = 4, requests_per_client: int = 8,
             max_wait_s: float = 0.01, max_batch_rows: int = 64,
             replicas: int = 0, procs: int = 0, chaos=None,
             service_config=None, **service_kwargs) -> dict:
    """Run the closed loop; returns a JSON-able report dict.

    `replicas` > 0 runs the loop against a FleetService of that many
    supervised replicas (kindel_tpu.fleet) instead of a single
    ConsensusService, and the report gains a `fleet` object (replica
    states + the kindel_fleet_* counters). `procs` > 0 instead runs it
    against a ProcessFleetService of that many replica PROCESSES over
    RPC (kindel_tpu.fleet.procreplica) and the report additionally
    gains an `rpc` object (call p50/p99, retries, dedupe hits, scale
    events). `chaos` is an optional callable invoked on its own thread
    once the clients start — `chaos(service)` — the fleet chaos
    suite's hook for killing and draining replicas mid-run.
    `service_config` merges extra ConsensusService knobs into each
    replica process's config (procs mode only — the durable-journal
    chaos suite passes journal_dir/quarantine_after through it). Every
    completed request's FASTA feeds `fasta_sha256` (digest over the
    sorted set of distinct outputs), so two runs are byte-comparable
    without shipping sequences around.
    """
    import hashlib

    from kindel_tpu.obs.metrics import default_registry
    from kindel_tpu.serve import ConsensusClient, ConsensusService

    tmp = None
    if bam_path is None:
        tmp = tempfile.TemporaryDirectory(prefix="kindel_serve_load_")
        bam_path = _synth_sam(Path(tmp.name) / "load.sam")
    payload = Path(bam_path).read_bytes()
    global_before = default_registry().snapshot()

    latencies: list[float] = []
    lat_lock = threading.Lock()
    errors: list[str] = []
    fastas: set[str] = set()
    chaos_errors: list[str] = []
    # the chaos hook (when given) joins the same start barrier as the
    # clients, so the kill/drain sequence begins exactly at load start
    start_barrier = threading.Barrier(clients + 1 + (1 if chaos else 0))

    if procs:
        from kindel_tpu.fleet.procreplica import ProcessFleetService

        replicas = procs  # the fleet-report path below applies as-is
        service = ProcessFleetService(
            replicas=procs,
            service_config=dict(
                max_wait_s=max_wait_s, max_batch_rows=max_batch_rows,
                decode_workers=2, **(service_config or {}),
            ),
            **service_kwargs,
        )
    elif replicas:
        from kindel_tpu.fleet import FleetService

        service = FleetService(
            replicas=replicas, max_wait_s=max_wait_s,
            max_batch_rows=max_batch_rows, **service_kwargs,
        )
    else:
        service = ConsensusService(
            max_wait_s=max_wait_s, max_batch_rows=max_batch_rows,
            **service_kwargs,
        )

    try:
        with service as svc:
            client = ConsensusClient(svc)
            client.consensus(payload, timeout=300)  # compile warmup

            def one_client():
                from kindel_tpu.io.fasta import format_fasta

                start_barrier.wait()
                for _ in range(requests_per_client):
                    t0 = time.perf_counter()
                    try:
                        records = client.consensus(payload, timeout=300)
                    except Exception as e:  # noqa: BLE001
                        with lat_lock:
                            errors.append(repr(e))
                        continue
                    with lat_lock:
                        latencies.append(time.perf_counter() - t0)
                        fastas.add(format_fasta(records))

            threads = [
                threading.Thread(target=one_client, name=f"load-client-{i}")
                for i in range(clients)
            ]
            chaos_thread = None
            if chaos is not None:
                def run_chaos():
                    start_barrier.wait()
                    try:
                        chaos(svc)
                    except Exception as e:  # noqa: BLE001
                        chaos_errors.append(repr(e))

                chaos_thread = threading.Thread(
                    target=run_chaos, name="load-chaos"
                )
                threads = threads + [chaos_thread]

            for t in threads:
                t.start()
            start_barrier.wait()
            t_start = time.perf_counter()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t_start
            if replicas:
                fleet_snap = svc.fleet_snapshot()
                snap = fleet_snap["totals"]
                # server-side dedupe lives in the CHILD processes'
                # registries; /v1/rpc carries it back while they are up
                remote_rpc = svc.rpc_stats() if procs else None
            else:
                fleet_snap = None
                remote_rpc = None
                snap = svc.metrics.snapshot()
    finally:
        if tmp is not None:
            tmp.cleanup()

    done = len(latencies)
    latencies.sort()

    def pct(q: float) -> float:
        if not latencies:
            return 0.0
        return latencies[min(done - 1, int(q * done))]

    occupancy = snap.get("kindel_serve_batch_occupancy", {})
    if not isinstance(occupancy, dict):
        occupancy = {}
    # warmup ran alone before the barrier: exclude it from the coalesce
    # ratio so the ratio reflects the loaded regime only
    dispatches = max(int(snap.get(
        "kindel_serve_device_dispatches_total", 0
    )) - 1, 1)
    digest = hashlib.sha256(
        "\n".join(sorted(fastas)).encode()
    ).hexdigest()
    report = {
        "clients": clients,
        "requests": clients * requests_per_client,
        "completed": done,
        "errors": len(errors),
        "wall_s": round(wall, 3),
        "throughput_rps": round(done / wall, 2) if wall > 0 else 0.0,
        "latency_p50_ms": round(pct(0.5) * 1e3, 2),
        "latency_p99_ms": round(pct(0.99) * 1e3, 2),
        "occupancy_mean": round(float(occupancy.get("mean", 0.0)), 2),
        "occupancy_max": int(occupancy.get("max", 0)),
        "device_dispatches": dispatches,
        "coalesce_ratio": round(done / dispatches, 2),
        "max_wait_ms": max_wait_s * 1e3,
        # byte-identity handle: distinct FASTA outputs (should be 1 for
        # a single-payload loop) + digest over the sorted set
        "fasta_distinct": len(fastas),
        "fasta_sha256": digest,
    }
    if chaos_errors:
        report["chaos_errors"] = chaos_errors
    if fleet_snap is not None:
        report["fleet"] = {
            "replicas": {
                rid: doc["state"]
                for rid, doc in fleet_snap["replicas"].items()
            },
            **{
                k.replace("kindel_fleet_", "").replace("_total", ""): int(v)
                for k, v in fleet_snap["fleet"].items()
                if k.endswith("_total") and isinstance(v, (int, float))
            },
        }
    if procs:
        report["rpc"] = rpc_report(
            global_before, default_registry().snapshot()
        )
        if remote_rpc is not None:
            # the children's own dedupe counts (the local registry only
            # sees dedupes served in THIS process, i.e. none for procs)
            report["rpc"]["dedup_hits"] += int(
                remote_rpc.get("dedup_hits", 0)
            )
            report["rpc"]["applied"] = int(remote_rpc.get("applied", 0))
    return report


def rpc_report(before: dict, after: dict) -> dict:
    """The wire posture of one run, as counter DELTAS against a
    snapshot taken at load start (the registry is process-global, so
    absolute values would smear runs together): exchanges by outcome,
    client call p50/p99, transport resubmissions, server-side dedupe
    hits, and autoscale events — the `rpc` object bench.py attaches
    alongside the `fleet` counters."""

    def delta(name: str) -> int:
        return int(after.get(name, 0)) - int(before.get(name, 0))

    def total(prefix: str, snap: dict, **match) -> int:
        out = 0
        for k, v in snap.items():
            if not (k == prefix or k.startswith(prefix + "{")):
                continue
            if match and not all(
                f'{mk}="{mv}"' in k for mk, mv in match.items()
            ):
                continue
            if isinstance(v, (int, float)):
                out += int(v)
        return out

    seconds = after.get("kindel_rpc_call_seconds", {})
    if not isinstance(seconds, dict):
        seconds = {}
    respawn_s = after.get("kindel_fleet_respawn_seconds", {})
    if not isinstance(respawn_s, dict):
        respawn_s = {}
    return {
        "calls": {
            outcome: (
                total("kindel_rpc_calls_total", after, outcome=outcome)
                - total("kindel_rpc_calls_total", before, outcome=outcome)
            )
            for outcome in ("ok", "shed", "deadline", "bad_request",
                            "error")
        },
        # quantiles over the histogram's recent window (absolute — the
        # window is bounded and dominated by this run's calls)
        "call_p50_ms": round(float(seconds.get("p50", 0.0)) * 1e3, 2),
        "call_p99_ms": round(float(seconds.get("p99", 0.0)) * 1e3, 2),
        "retries": (
            total("kindel_retry_total", after, site="rpc.call",
                  outcome="retried")
            - total("kindel_retry_total", before, site="rpc.call",
                    outcome="retried")
        ),
        "dedup_hits": delta("kindel_rpc_dedup_hits_total"),
        "scale_events": {
            "up": (
                total("kindel_fleet_scale_events_total", after,
                      direction="up")
                - total("kindel_fleet_scale_events_total", before,
                        direction="up")
            ),
            "down": (
                total("kindel_fleet_scale_events_total", after,
                      direction="down")
                - total("kindel_fleet_scale_events_total", before,
                        direction="down")
            ),
        },
        "respawns": delta("kindel_fleet_respawns_total"),
        # spawn→ready wall per process generation (the respawn-latency
        # satellite): how long a recovery-from-host-loss actually takes,
        # from the same recent-window quantiles as the call latencies
        "respawn_p50_ms": round(float(respawn_s.get("p50", 0.0)) * 1e3, 2),
        "respawn_p99_ms": round(float(respawn_s.get("p99", 0.0)) * 1e3, 2),
    }


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--bam", default=None,
                    help="SAM/BAM to serve (default: synthetic)")
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--requests", type=int, default=8,
                    help="requests per client")
    ap.add_argument("--max-wait-ms", type=float, default=10.0)
    ap.add_argument("--replicas", type=int, default=0,
                    help="run against a FleetService of N supervised "
                         "replicas (kindel_tpu.fleet); 0 = single service")
    ap.add_argument("--procs", type=int, default=0,
                    help="run against a ProcessFleetService of N replica "
                         "PROCESSES over RPC "
                         "(kindel_tpu.fleet.procreplica); 0 = off")
    args = ap.parse_args(argv)
    report = run_load(
        bam_path=args.bam, clients=args.clients,
        requests_per_client=args.requests,
        max_wait_s=args.max_wait_ms / 1e3,
        replicas=args.replicas,
        procs=args.procs,
    )
    print(json.dumps(report))
    return 0 if report["errors"] == 0 else 1


if __name__ == "__main__":
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    sys.exit(main())
