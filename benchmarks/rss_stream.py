"""Flat-RSS demonstration for the streamed single-file decode.

Synthesizes a large BAM (vectorized — fixed-length reads, BGZF-compatible
gzip members), then measures peak RSS and wall time for the slurped vs the
streamed consensus path in separate child processes.

    python benchmarks/rss_stream.py [--gb 1.0] [--chunk-mb 64]

Prints one JSON line per mode: {"mode", "max_rss_mb", "wall_s", "mbases"}.
"""

from __future__ import annotations

import argparse
import gzip
import json
import os
import struct
import subprocess
import sys
import time
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

READ_LEN = 140
REC_BYTES = 252  # 4 block_size + 32 fixed + 2 name + 4 cigar + 70 seq + 140 qual


def synthesize(path: Path, target_bytes: int, ref_len: int = 6_097_032,
               seed: int = 0) -> int:
    """Write a gzip-member-chunked BAM of ~target_bytes decompressed size.
    Returns the read count. All reads are 140M (fixed CIGAR) with random
    positions/sequences — the same shape as the bacterial benchmark."""
    n_reads = max(target_bytes // REC_BYTES, 1)
    rng = np.random.default_rng(seed)

    name = b"SYNTH1\x00"
    header_text = f"@SQ\tSN:SYNTH1\tLN:{ref_len}\n".encode()
    hdr = b"BAM\x01" + struct.pack("<i", len(header_text)) + header_text
    hdr += struct.pack("<i", 1)
    hdr += struct.pack("<i", len(name)) + name + struct.pack("<i", ref_len)

    fixed = np.zeros((1, REC_BYTES), dtype=np.uint8)
    fixed[0, 0:4] = np.frombuffer(
        struct.pack("<i", REC_BYTES - 4), dtype=np.uint8
    )
    # refID=0, pos filled later, l_read_name=2, mapq=60, bin=0, n_cigar=1,
    # flag=0, l_seq, next_refID=-1, next_pos=-1, tlen=0
    body = struct.pack(
        "<iiBBHHHiiii", 0, 0, 2, 60, 0, 1, 0, READ_LEN, -1, -1, 0
    )
    fixed[0, 4:36] = np.frombuffer(body, dtype=np.uint8)
    fixed[0, 36:38] = np.frombuffer(b"r\x00", dtype=np.uint8)
    fixed[0, 38:42] = np.frombuffer(
        struct.pack("<I", (READ_LEN << 4) | 0), dtype=np.uint8
    )
    fixed[0, 112:252] = 0xFF  # qual

    nib_codes = np.array([1, 2, 4, 8], dtype=np.uint8)  # A C G T

    with open(path, "wb") as fh:
        fh.write(gzip.compress(hdr, 1))
        batch = 200_000
        done = 0
        while done < n_reads:
            b = min(batch, n_reads - done)
            out = np.repeat(fixed, b, axis=0)
            pos = rng.integers(
                0, ref_len - READ_LEN, size=b, dtype=np.int32
            )
            out[:, 8:12] = pos.view(np.uint8).reshape(b, 4)
            nibs = nib_codes[
                rng.integers(0, 4, size=(b, READ_LEN), dtype=np.int8)
            ]
            out[:, 42:112] = (nibs[:, 0::2] << 4) | nibs[:, 1::2]
            fh.write(gzip.compress(out.tobytes(), 1))
            done += b
    return int(n_reads)


_CHILD = r"""
import json, resource, sys, time
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
from kindel_tpu.workloads import bam_to_consensus
t0 = time.perf_counter()
res = bam_to_consensus({bam!r}, backend={backend!r},
                       stream_chunk_mb={chunk!r})
wall = time.perf_counter() - t0
rss_mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024
sharded = False
if {mesh!r}:
    import kindel_tpu.parallel.stream_product as sp
    sharded = sp._zeros_sharded._cache_size() > 0  # jit ran => mesh engaged
seq = res.consensuses[0].sequence
print(json.dumps({{"mode": {mode!r}, "max_rss_mb": round(rss_mb, 1),
                  "wall_s": round(wall, 2), "n_devices": len(jax.devices()),
                  "sharded": sharded,
                  "digest": __import__("hashlib").sha256(seq.encode()).hexdigest()[:16],
                  "mbases": round(len(seq) / 1e6, 2)}}))
"""


def measure(bam: Path, mode: str, backend: str, chunk_mb,
            mesh: int = 0) -> dict:
    code = _CHILD.format(
        repo=str(REPO), bam=str(bam), backend=backend, chunk=chunk_mb,
        mode=mode, mesh=mesh,
    )
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    if mesh:
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={mesh}"
        ).strip()
    # keep autostream out of the slurp arm
    env["KINDEL_TPU_STREAM_THRESHOLD_MB"] = "1000000"
    out = subprocess.run(
        [sys.executable, "-c", code], env=env, capture_output=True,
        text=True, check=True,
    )
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    print(json.dumps(rec))
    return rec


def measure_two_process(bam: Path, chunk_mb) -> list[dict]:
    """Launch a real 2-process JAX group (localhost coordinator, 4 virtual
    devices each) running the streamed×sharded path via _rss_dist_worker;
    returns both workers' JSON records (per-process peak RSS + digest).
    Uses the shared harness in tests/distfixture.py (port reservation +
    bind-race retry + cleanup) so a transient port steal cannot abort a
    long benchmark run."""
    sys.path.insert(0, str(REPO / "tests"))
    import distfixture

    worker = Path(__file__).parent / "_rss_dist_worker.py"
    outs = distfixture.run_two_process(
        worker, extra_argv=(bam, chunk_mb), timeout=3600,
    )
    recs = []
    for _rc, out, _err in outs:
        rec = json.loads(out.strip().splitlines()[-1])
        print(json.dumps(rec))
        recs.append(rec)
    return recs


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--gb", type=float, default=1.0,
                    help="decompressed size of the synthetic BAM")
    ap.add_argument("--ref-len", type=int, default=6_097_032,
                    help="reference length of the synthetic BAM (the "
                         "position axis is the cost driver; 1e8 for the "
                         "scale-headroom proof)")
    ap.add_argument("--chunk-mb", type=float, default=64.0)
    ap.add_argument("--backend", default="jax")
    ap.add_argument("--mesh", type=int, default=8, metavar="N",
                    help="also run the streamed path on an N-device "
                         "virtual CPU mesh and assert sharded execution + "
                         "identical output (0 disables)")
    ap.add_argument("--procs", type=int, default=0, choices=(0, 2),
                    help="also run a REAL 2-process JAX group (sp=8 "
                         "spanning both) and report per-process peak RSS "
                         "(the shard-local host-memory proof)")
    ap.add_argument("--keep", action="store_true")
    args = ap.parse_args()

    bam = Path(f"/tmp/kindel_tpu_rss_synth_{args.ref_len}.bam")
    target = int(args.gb * (1 << 30))
    if not bam.exists() or abs(bam.stat().st_size * 3 - target) > target:
        t0 = time.perf_counter()
        n = synthesize(bam, target, ref_len=args.ref_len)
        print(
            f"# synthesized {n} reads, {bam.stat().st_size / 1e6:.0f} MB "
            f"compressed in {time.perf_counter() - t0:.1f}s",
            file=sys.stderr,
        )

    slurp = measure(bam, "slurp", args.backend, None)
    stream = measure(bam, "stream", args.backend, args.chunk_mb)
    ratio = slurp["max_rss_mb"] / max(stream["max_rss_mb"], 1)
    print(
        f"# rss {slurp['max_rss_mb']:.0f} -> {stream['max_rss_mb']:.0f} MB "
        f"({ratio:.1f}x), wall {slurp['wall_s']} -> {stream['wall_s']} s",
        file=sys.stderr,
    )
    if args.mesh and args.backend != "jax":
        print("# mesh arm skipped: requires --backend jax", file=sys.stderr)
        args.mesh = 0
    if args.mesh:
        meshed = measure(
            bam, f"stream+mesh{args.mesh}", args.backend, args.chunk_mb,
            mesh=args.mesh,
        )
        same = meshed["digest"] == stream["digest"] == slurp["digest"]
        print(
            f"# mesh{args.mesh}: rss {meshed['max_rss_mb']:.0f} MB, "
            f"wall {meshed['wall_s']} s, sharded={meshed['sharded']}, "
            f"output identical={same}",
            file=sys.stderr,
        )
        if not (same and meshed["sharded"]):
            sys.exit(1)
    if args.procs:
        recs = measure_two_process(bam, args.chunk_mb)
        same = all(r["digest"] == stream["digest"] for r in recs)
        peak = max(r["max_rss_mb"] for r in recs)
        print(
            f"# 2-process: per-process peak rss "
            f"{[r['max_rss_mb'] for r in recs]} MB (vs single-process "
            f"streamed {stream['max_rss_mb']:.0f} MB), output "
            f"identical={same}",
            file=sys.stderr,
        )
        if not (same and peak < stream["max_rss_mb"]):
            sys.exit(1)
    if not args.keep:
        bam.unlink()


if __name__ == "__main__":
    main()
