"""Pod sweep: the pod-mesh matrix benched — dp × procs, identity asserted.

The pod data plane (kindel_tpu.parallel.meshexec, DESIGN.md §27) spans
one mesh across every process of a JAX distributed group. This scenario
runs the fixed pod cohort (tests/podfixture.py — the same drivers the
byte-identity tests pin) through all three dispatch tiers at each
configuration:

  * the dp=1 single-device oracle,
  * degraded single-process pod plans (``pod:2``, ``pod:4``),
  * an actual localhost 2-process group at dp ∈ {2, 4} (4 virtual CPU
    devices per process, coordinator + gloo brought up by the plan
    builder from the `--mesh pod:<dp>` knob surface alone),

and reports per-config wall, the cross-process allgather byte tax
(`kindel_pod_allgather_bytes_total` — the pod tier's only DCN
transfer), and whether every configuration's FASTA digests matched the
oracle (a sweep that silently changed the answer would be worse than
no sweep). Every configuration runs in a fresh process, so each wall
includes its own compile — the comparison is config-vs-config, not
warm-vs-cold. `bench.py` attaches the report as its `pod` object
(`KINDEL_TPU_BENCH_POD` overrides the CPU-only default);
`MULTICHIP_r07.json` records one run. The perf gate reads the
2-process dp=2 tier walls as the `(cpu, pod_dp2)` series.

Standalone:

    python -m benchmarks.pod_sweep
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

#: (spec, procs) sweep points; the first is the byte-identity oracle
SWEEP = (
    ("1", 1),
    ("pod:2", 1),
    ("pod:4", 1),
    ("pod:2", 2),
    ("pod:4", 2),
)


def _run_single(spec: str, tmpdir: str, realign: bool) -> dict:
    """One single-process configuration in a fresh interpreter (its own
    jit cache — walls comparable across configs)."""
    worker = Path(__file__).parent / "_pod_bench_worker.py"
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    argv = [sys.executable, str(worker), "0", "0", spec, tmpdir, "1"]
    if realign:
        argv.append("realign")
    out = subprocess.run(
        argv, env=env, capture_output=True, text=True, check=True,
        cwd=str(REPO),
    )
    return json.loads(out.stdout.strip().splitlines()[-1])


def _run_pair(spec: str, tmpdir: str, realign: bool) -> list[dict]:
    """One 2-process configuration through the shared harness in
    tests/distfixture.py (port reservation + bind-race retry +
    cleanup)."""
    sys.path.insert(0, str(REPO / "tests"))
    import distfixture

    worker = Path(__file__).parent / "_pod_bench_worker.py"
    extra = [spec, tmpdir, "2"]
    if realign:
        extra.append("realign")
    outs = distfixture.run_two_process(
        worker, extra_argv=tuple(extra), timeout=1800,
    )
    return [
        json.loads(out.strip().splitlines()[-1])
        for _rc, out, _err in outs
    ]


def run_pod_sweep(realign: bool = False, sweep=SWEEP) -> dict:
    """Run every sweep point; returns {"identical": ..., "configs":
    [...]} with the oracle first."""
    tmp = tempfile.TemporaryDirectory(prefix="kindel_pod_sweep_")
    try:
        configs: list[dict] = []
        oracle: dict | None = None
        identical = True
        for spec, procs in sweep:
            sub = os.path.join(
                tmp.name, f"{spec.replace(':', '_')}_p{procs}"
            )
            if procs == 1:
                recs = [_run_single(spec, sub, realign)]
            else:
                recs = _run_pair(spec, sub, realign)
            entry = {
                "spec": spec,
                "procs": procs,
                "dp": recs[0]["dp"],
                "wall_s": max(r["wall_s"] for r in recs),
                "allgather_bytes": sum(
                    r["allgather_bytes"] for r in recs
                ),
                "digests": recs[0]["digests"],
            }
            if any(r["digests"] != recs[0]["digests"] for r in recs):
                identical = False
                entry["disagreement"] = "workers diverged"
            if oracle is None:
                oracle = entry
            elif entry["digests"] != oracle["digests"]:
                identical = False
                entry["disagreement"] = "diverged from oracle"
            configs.append(entry)
        for entry in configs:
            entry.pop("digests", None)
        return {
            "realign": realign,
            "identical": identical,
            "configs": configs,
        }
    finally:
        tmp.cleanup()


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--realign", action="store_true")
    args = ap.parse_args(argv)
    report = run_pod_sweep(realign=args.realign)
    json.dump(report, sys.stdout, indent=1)
    print()
    return 0 if report["identical"] else 1


if __name__ == "__main__":
    sys.exit(main())
