"""Open-loop paged-serving scenario: continuous superbatching vs the
flush-barrier modes.

`ragged_load.py` replays a closed loop — every client waits for its
previous answer, so the service never sees the bursty, mixed-size
arrival process that motivates per-segment admit/retire. This scenario
submits an OPEN-LOOP arrival stream (fixed inter-arrival, nobody
waits) of two mixes:

  * straggler-heavy: mostly small segments with periodic large ones —
    the regime where a sealed superbatch holds everyone behind its
    biggest member, and where paged retirement should beat the ragged
    flush barrier on tail latency;
  * amplicon: one payload replayed many times (same reference, same
    reads — surveillance traffic) — the regime the reference-panel
    cache dedupes, so the paged run should show a non-zero panel hit
    rate.

The identical request set runs through lanes, ragged, and paged modes;
byte-identity across modes is asserted on every run, and the report
records per mode: occupancy (payload/padded bases), dispatch counts,
client-observed p50/p99 latency, jit-cache entries — plus, for paged,
retire p50/p99, residency, and the panel hit rate. `bench.py` attaches
the report as its `paged` object (KINDEL_TPU_BENCH_PAGED opt-in).

Standalone:

    python -m benchmarks.paged_load --requests 18
"""

from __future__ import annotations

import json
import sys
import tempfile
import threading
import time
from pathlib import Path


def make_payloads(out_dir: Path, n: int = 18, seed: int = 0) -> list:
    """(kind, bytes) arrival list: a straggler-heavy mixed-size stream
    with an amplicon tail — every third small payload is a REPLAY of
    one fixed amplicon sample (identical bytes → panel-cache hits)."""
    import numpy as np

    from benchmarks.ragged_load import make_mixed_sams

    rng = np.random.default_rng(seed)
    mixed = [
        p.read_bytes()
        for p in make_mixed_sams(out_dir, max(4, n // 3), seed)
    ]
    # one big straggler payload: a reference ~10× the small ones,
    # long-read shaped (few long alignments — one op span each, so the
    # segment's span footprint stays inside its page run's per-page
    # quota and the delta-residency path serves it; see
    # kindel_tpu.paged.residency quotas)
    lines = ["@HD\tVN:1.6", "@SQ\tSN:strag\tLN:24000"]
    for j in range(40):
        pos = int(rng.integers(0, 24000 - 620))
        seq = "".join("ACGT"[b] for b in rng.integers(0, 4, size=600))
        lines.append(
            f"s{j}\t0\tstrag\t{pos + 1}\t60\t600M\t*\t0\t0\t{seq}\t*"
        )
    straggler = ("\n".join(lines) + "\n").encode()
    amplicon = mixed[0]
    payloads = []
    for i in range(n):
        if i % 6 == 5:
            payloads.append(("straggler", straggler))
        elif i % 3 == 0:
            payloads.append(("amplicon", amplicon))
        else:
            payloads.append(("mixed", mixed[i % len(mixed)]))
    return payloads


def _counter_totals(snapshot: dict, prefix: str) -> float:
    return sum(
        float(v) for k, v in snapshot.items()
        if (k == prefix or k.startswith(prefix + "{"))
        and not isinstance(v, dict)
    )


def _global_snapshot() -> dict:
    from kindel_tpu.obs.metrics import default_registry

    return default_registry().snapshot()


def run_open_loop(requests: int = 18, seed: int = 0,
                  arrival_ms: float = 4.0,
                  max_wait_s: float = 0.03) -> dict:
    """Run the open-loop arrival stream through all three batch modes;
    returns the comparison report (see module docstring)."""
    from kindel_tpu.obs import runtime as obs_runtime
    from kindel_tpu.serve import ConsensusService
    from kindel_tpu.tune import TuningConfig

    tmp = tempfile.TemporaryDirectory(prefix="kindel_paged_load_")
    try:
        payloads = make_payloads(Path(tmp.name), requests, seed)

        def run_mode(mode: str, emit: str = "host"):
            from kindel_tpu.io.fasta import format_fasta

            snap0 = _global_snapshot()
            cache0 = obs_runtime.jit_cache_sizes()
            results: list = [None] * len(payloads)
            latencies: list = [None] * len(payloads)
            errors: list = []
            with ConsensusService(
                tuning=TuningConfig(batch_mode=mode, emit_mode=emit),
                max_wait_s=max_wait_s, decode_workers=4,
            ) as svc:
                # warm outside the measured window (compile walls would
                # swamp an open-loop latency comparison on CPU)
                svc.request(payloads[0][1], timeout=600)
                t_submit: list = [0.0] * len(payloads)
                futs = []
                t_start = time.perf_counter()
                for i, (_kind, body) in enumerate(payloads):
                    t_submit[i] = time.perf_counter()
                    futs.append(svc.submit(body))
                    time.sleep(arrival_ms / 1e3)  # open loop: no waiting

                def settle(i, fut):
                    try:
                        res = fut.result(timeout=600)
                        latencies[i] = time.perf_counter() - t_submit[i]
                        results[i] = format_fasta(res.consensuses)
                    except Exception as e:  # noqa: BLE001
                        errors.append((i, repr(e)))

                threads = [
                    threading.Thread(target=settle, args=(i, f))
                    for i, f in enumerate(futs)
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                wall = time.perf_counter() - t_start
                svc_snap = svc.metrics.snapshot()
            snap1 = _global_snapshot()
            cache1 = obs_runtime.jit_cache_sizes()

            def delta(prefix):
                return _counter_totals(snap1, prefix) - _counter_totals(
                    snap0, prefix
                )

            lat = sorted(v for v in latencies if v is not None)

            def pct(q):
                return (
                    lat[min(len(lat) - 1, int(q * len(lat)))]
                    if lat else 0.0
                )

            payload = delta("kindel_dispatch_payload_bases_total")
            padded = delta("kindel_dispatch_padded_bases_total")
            d2h = delta("kindel_device_d2h_bytes_total")
            report = {
                "errors": len(errors),
                # the transfer claims (ISSUE 13): h2d/d2h over the whole
                # mode run plus the paged split (delta-admission patches
                # vs classic full re-assembly uploads) — d2h_per_request
                # is the number the device-emission wire collapses to
                # ~O(consensus length)
                "transfers": {
                    "emit_mode": emit,
                    "h2d_bytes": int(
                        delta("kindel_device_h2d_bytes_total")
                    ),
                    "d2h_bytes": int(d2h),
                    "d2h_per_request": int(d2h / max(1, len(payloads))),
                    "admit_h2d_bytes": int(
                        delta("kindel_paged_admit_h2d_bytes_total")
                    ),
                    "launch_h2d_bytes": int(
                        delta("kindel_paged_launch_h2d_bytes_total")
                    ),
                },
                "wall_s": round(wall, 3),
                "dispatches": int(
                    svc_snap.get("kindel_serve_device_dispatches_total", 0)
                ),
                "payload_bases": int(payload),
                "padded_bases": int(padded),
                "occupancy": round(payload / padded, 4) if padded else 0.0,
                "latency_p50_ms": round(pct(0.5) * 1e3, 2),
                "latency_p99_ms": round(pct(0.99) * 1e3, 2),
                "jit_cache_entries": sum(cache1.values())
                - sum(cache0.values()),
            }
            if mode == "paged":
                retire = snap1.get("kindel_paged_retire_seconds", {})
                residency = snap1.get("kindel_paged_residency", {})
                hits = delta("kindel_paged_panel_hits_total")
                misses = delta("kindel_paged_panel_misses_total")
                report.update({
                    "launches": int(
                        delta("kindel_paged_launches_total")
                    ),
                    "retires": int(
                        retire.get("count", 0) if isinstance(retire, dict)
                        else 0
                    ),
                    "retire_p50_ms": round(
                        float(retire.get("p50", 0.0)) * 1e3, 2
                    ) if isinstance(retire, dict) else 0.0,
                    "retire_p99_ms": round(
                        float(retire.get("p99", 0.0)) * 1e3, 2
                    ) if isinstance(retire, dict) else 0.0,
                    "residency_mean": round(
                        float(residency.get("mean", 0.0)), 4
                    ) if isinstance(residency, dict) else 0.0,
                    "panel_hits": int(hits),
                    "panel_hit_rate": round(
                        hits / (hits + misses), 4
                    ) if hits + misses else 0.0,
                })
            if mode == "ragged":
                # the flush barrier paged retirement is measured against:
                # client-observed dispatch latency of the sealed
                # superbatches (per-shape histograms, worst p99)
                flush_p99 = 0.0
                for k, v in svc_snap.items():
                    if k.startswith("kindel_serve_dispatch_seconds") and (
                        isinstance(v, dict)
                    ):
                        flush_p99 = max(flush_p99, float(v.get("p99", 0.0)))
                report["flush_p99_ms"] = round(flush_p99 * 1e3, 2)
            return results, report

        out: dict = {"requests": requests, "arrival_ms": arrival_ms}
        fastas = {}
        for mode in ("lanes", "ragged", "paged"):
            fastas[mode], out[mode] = run_mode(mode)
        # the emission tentpole's measured half (ISSUE 13): the same
        # paged stream under --emit-mode device — identity asserted
        # against every other run, d2h compared against host emission
        fastas["paged:emit"], out["paged_emit"] = run_mode(
            "paged", emit="device"
        )
        out["identical"] = (
            fastas["lanes"] == fastas["ragged"] == fastas["paged"]
            == fastas["paged:emit"]
        )
        # the acceptance claims, recorded (not asserted — perf claims
        # belong to the bench record; identity is the hard gate)
        host_tr = out["paged"]["transfers"]
        emit_tr = out["paged_emit"]["transfers"]
        out["claims"] = {
            "paged_occupancy_ge_ragged": (
                out["paged"]["occupancy"] >= out["ragged"]["occupancy"]
            ),
            "paged_retire_p99_lt_ragged_flush_p99": (
                out["paged"].get("retire_p99_ms", 0.0)
                < out["ragged"].get("flush_p99_ms", float("inf"))
            ),
            "panel_hit_rate_nonzero": (
                out["paged"].get("panel_hit_rate", 0.0) > 0.0
            ),
            # (b) per-tick h2d ∝ newly-admitted segments only: the
            # delta-admission patches carry the paged upload and the
            # classic full re-assembly path never fires
            "paged_h2d_is_delta_only": (
                host_tr["admit_h2d_bytes"] > 0
                and host_tr["launch_h2d_bytes"] == 0
            ),
            # (a) d2h per request collapses under device emission vs
            # the wire-plane download
            "emit_d2h_per_request_lt_host": (
                emit_tr["d2h_per_request"] < host_tr["d2h_per_request"]
            ),
        }
        return out
    finally:
        tmp.cleanup()


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=18)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--arrival-ms", type=float, default=4.0)
    args = ap.parse_args(argv)
    report = run_open_loop(
        requests=args.requests, seed=args.seed,
        arrival_ms=args.arrival_ms,
    )
    print(json.dumps(report))
    return 0 if report["identical"] else 1


if __name__ == "__main__":
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    sys.exit(main())
