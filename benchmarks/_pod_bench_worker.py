"""Worker for the pod sweep (benchmarks/pod_sweep.py): one process of a
pod-mesh bench configuration. With ``nprocs=2`` it joins a localhost
2-process JAX group (4 virtual CPU devices each) exactly like the test
harness (tests/_dist_pod_worker.py) — the plan builder brings the group
up from the `--mesh pod:<dp>` knob surface alone; with ``nprocs=1`` it
runs the degraded single-process plan (the oracle when the spec is
``1``). Drives all three dispatch tiers through the shared podfixture
drivers and prints ONE JSON line: wall, digests, and the pod allgather
byte tax.

Usage:
  python benchmarks/_pod_bench_worker.py <proc_id> <port> <spec> \
      <tmpdir> <nprocs> [realign]

(underscore prefix: not collected by pytest)."""

import json
import os
import sys
import time

proc_id = int(sys.argv[1])
port = int(sys.argv[2])
spec = sys.argv[3]
tmpdir = sys.argv[4]
nprocs = int(sys.argv[5])
realign = len(sys.argv) > 6 and sys.argv[6] == "realign"

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=" + (
    "4" if nprocs == 2 else "8"
)
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
if nprocs == 2:
    os.environ["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
    os.environ["JAX_NUM_PROCESSES"] = "2"
    os.environ["JAX_PROCESS_ID"] = str(proc_id)
os.environ["KINDEL_TPU_MESH"] = spec
os.environ["KINDEL_TPU_TUNE_CACHE"] = os.path.join(
    tmpdir, f"proc{proc_id}", "tune.json"
)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

_here = os.path.dirname(os.path.abspath(__file__))
_repo = os.path.dirname(_here)
sys.path.insert(0, _repo)
sys.path.insert(0, os.path.join(_repo, "tests"))

from tests import podfixture  # noqa: E402
from kindel_tpu.obs.metrics import default_registry  # noqa: E402
from kindel_tpu.parallel import meshexec  # noqa: E402

plan = meshexec.plan()
assert plan.procs == nprocs, f"wanted {nprocs} processes, got {plan}"

t0 = time.perf_counter()
digests = podfixture.all_digests(
    os.path.join(tmpdir, f"proc{proc_id}", "sams"), plan,
    realign=realign,
)
wall = time.perf_counter() - t0
snap = default_registry().snapshot()
print(json.dumps({
    "proc": proc_id,
    "spec": spec,
    "procs": plan.procs,
    "dp": plan.dp,
    "realign": realign,
    "wall_s": round(wall, 3),
    "allgather_bytes": int(
        snap.get("kindel_pod_allgather_bytes_total", 0)
    ),
    "digests": digests,
}), flush=True)
