"""Worker for the 2-process shard-local-RSS proof (rss_stream.py --procs 2).

Joins a localhost 2-process JAX group (4 virtual CPU devices each, sp=8
global mesh spanning the boundary), streams the synthetic BAM in chunks
into position-sharded device state, closes through the product kernel, and
prints one JSON line: per-process peak RSS, wall, and the consensus
digest. Each process allocates only its own 4 shards of the global count
state — the point of the run is that peak RSS per process drops well
under the single-process figure at the same reference length (VERDICT r4
item 4: the reference holds everything in RAM on every rank,
kindel.py:143-148).

Usage: python benchmarks/_rss_dist_worker.py <proc_id> <port> <bam> <chunk_mb>
"""

import json
import os
import resource
import sys
import time

proc_id = int(sys.argv[1])
port = int(sys.argv[2])
bam = sys.argv[3]
chunk_bytes = int(float(sys.argv[4]) * (1 << 20))

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kindel_tpu.parallel import initialize_distributed  # noqa: E402

assert initialize_distributed(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2,
    process_id=proc_id,
) is True
assert jax.process_count() == 2 and jax.device_count() == 8

from jax.sharding import Mesh  # noqa: E402

from kindel_tpu.io.stream import stream_alignment  # noqa: E402
from kindel_tpu.parallel.product import close_sharded_ref  # noqa: E402
from kindel_tpu.parallel.stream_product import (  # noqa: E402
    ShardedStreamAccumulator,
)

mesh = Mesh(jax.devices(), ("sp",))
assert {d.process_index for d in mesh.devices.flat} == {0, 1}

t0 = time.perf_counter()
acc = ShardedStreamAccumulator(mesh=mesh, full=False)
n_chunks = 0
for batch in stream_alignment(bam, chunk_bytes):
    acc.add_batch(batch)
    n_chunks += 1
rid = next(iter(acc.present))
sr = acc.finish(rid, min_depth=1)
res, dmin, dmax, _cdr = close_sharded_ref(
    sr, realign=False, min_depth=1, min_overlap=9,
    clip_decay_threshold=0.1, mask_ends=50, trim_ends=False,
    uppercase=False,
)
wall = time.perf_counter() - t0
rss_mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024
import hashlib  # noqa: E402

print(json.dumps({
    "mode": f"stream+2proc/p{proc_id}",
    "max_rss_mb": round(rss_mb, 1),
    "wall_s": round(wall, 2),
    "n_chunks": n_chunks,
    "local_devices": len(jax.local_devices()),
    "digest": hashlib.sha256(res.sequence.encode()).hexdigest()[:16],
    "mbases": round(len(res.sequence) / 1e6, 2),
}), flush=True)
