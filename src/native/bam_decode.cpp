// Native BAM decode helpers for kindel-tpu.
//
// The only data-dependent sequential stage of L0 is walking BAM record
// boundaries (each record's offset depends on the previous block_size) —
// everything downstream is vectorized numpy / device code. This walk is done
// here in C++; a BGZF block inflater is included for large inputs where
// Python's gzip member loop becomes measurable.
//
// Exposed via ctypes (kindel_tpu/io/native.py). Build: make -C src/native

#include <cstdint>
#include <cstring>

#include <zlib.h>

extern "C" {

// Walk alignment-record boundaries of a decompressed BAM stream.
// `start` is the byte offset of the first record (after header+refs).
// Writes record-body offsets (start of refID field) into `out` (capacity
// `cap`). Returns the number of records, or -1 on malformed input / -2 if
// capacity is exhausted.
int64_t bam_scan_offsets(const uint8_t* data, int64_t len, int64_t start,
                         int64_t* out, int64_t cap) {
    int64_t off = start;
    int64_t n = 0;
    while (off + 4 <= len) {
        int32_t block_size;
        std::memcpy(&block_size, data + off, 4);
        if (block_size < 32 || off + 4 + block_size > len) return -1;
        if (n >= cap) return -2;
        out[n++] = off + 4;
        off += 4 + static_cast<int64_t>(block_size);
    }
    return n;
}

// Inflate a BGZF byte stream (concatenated gzip members with BC extra
// fields). Returns the decompressed size, or -1 on error / -2 if `out_cap`
// is too small. Each member's payload sits between the 18-byte BGZF header
// and the 8-byte CRC/ISIZE trailer; ISIZE gives the member's output size.
int64_t bgzf_inflate(const uint8_t* data, int64_t len, uint8_t* out,
                     int64_t out_cap) {
    int64_t off = 0;
    int64_t written = 0;
    while (off < len) {
        if (off + 18 > len) return -1;
        if (data[off] != 0x1f || data[off + 1] != 0x8b) return -1;
        // find BSIZE in the extra field (FLG.FEXTRA with "BC" subfield)
        if (!(data[off + 3] & 4)) return -1;
        uint16_t xlen;
        std::memcpy(&xlen, data + off + 10, 2);
        // clamp the extra-field walk to the buffer: xlen is untrusted and
        // off+12+xlen can lie past the end of a truncated member
        int64_t xoff = off + 12, xend = xoff + xlen;
        if (xend > len) xend = len;
        int64_t bsize = -1;
        while (xoff + 4 <= xend) {
            uint8_t si1 = data[xoff], si2 = data[xoff + 1];
            uint16_t slen;
            std::memcpy(&slen, data + xoff + 2, 2);
            if (si1 == 66 && si2 == 67 && slen == 2) {
                if (xoff + 6 > len) return -1;
                uint16_t bs;
                std::memcpy(&bs, data + xoff + 4, 2);
                bsize = static_cast<int64_t>(bs) + 1;
                break;
            }
            xoff += 4 + slen;
        }
        if (bsize < 26 || off + bsize > len) return -1;
        uint32_t isize;
        std::memcpy(&isize, data + off + bsize - 4, 4);
        if (written + isize > out_cap) return -2;

        z_stream zs;
        std::memset(&zs, 0, sizeof(zs));
        if (inflateInit2(&zs, -15) != Z_OK) return -1;
        zs.next_in = const_cast<uint8_t*>(data + off + 18);
        zs.avail_in = static_cast<uInt>(bsize - 26);
        zs.next_out = out + written;
        zs.avail_out = static_cast<uInt>(out_cap - written);
        int rc = inflate(&zs, Z_FINISH);
        uLong total_out = zs.total_out;
        inflateEnd(&zs);
        if (rc != Z_STREAM_END || total_out != isize) return -1;
        written += isize;
        off += bsize;
    }
    return written;
}

// Sum of ISIZE fields — exact decompressed size for preallocation.
int64_t bgzf_decompressed_size(const uint8_t* data, int64_t len) {
    int64_t off = 0;
    int64_t total = 0;
    while (off < len) {
        if (off + 18 > len || data[off] != 0x1f || data[off + 1] != 0x8b ||
            !(data[off + 3] & 4))
            return -1;
        uint16_t xlen;
        std::memcpy(&xlen, data + off + 10, 2);
        // same untrusted-xlen clamp as bgzf_inflate
        int64_t xoff = off + 12, xend = xoff + xlen;
        if (xend > len) xend = len;
        int64_t bsize = -1;
        while (xoff + 4 <= xend) {
            uint16_t slen;
            std::memcpy(&slen, data + xoff + 2, 2);
            if (data[xoff] == 66 && data[xoff + 1] == 67 && slen == 2) {
                if (xoff + 6 > len) return -1;
                uint16_t bs;
                std::memcpy(&bs, data + xoff + 4, 2);
                bsize = static_cast<int64_t>(bs) + 1;
                break;
            }
            xoff += 4 + slen;
        }
        // bsize < 26 (18-byte header + 8-byte trailer) would place the
        // ISIZE read before the member start — the exploitable OOB read
        // this round's ASan fuzz caught (the inflate path already had the
        // stricter bound; the size pre-pass only rejected negatives)
        if (bsize < 26 || off + bsize > len) return -1;
        uint32_t isize;
        std::memcpy(&isize, data + off + bsize - 4, 4);
        total += isize;
        off += bsize;
    }
    return total;
}

// ---------------------------------------------------------------------------
// Hot-path expansion kernels. The numpy formulations of these (io/records.py,
// events.py, io/bam.py) are multi-pass over large int64 temporaries; each
// kernel below is one sequential-write pass. All are optional: Python keeps
// byte-identical fallbacks and uses these only when the library loads.

// Flat gather indices for ragged ranges [starts[i], starts[i]+lens[i]).
// Mirrors kindel_tpu.io.records.ragged_indices. Returns elements written,
// or -1 on any negative length (the caller allocates sum(lens); a negative
// entry makes that smaller than the elements the positive entries write,
// so writing anything would overrun the allocation).
int64_t ragged_indices64(const int64_t* starts, const int64_t* lens,
                         int64_t n, int64_t* out) {
    for (int64_t i = 0; i < n; ++i)
        if (lens[i] < 0) return -1;
    int64_t k = 0;
    for (int64_t i = 0; i < n; ++i) {
        const int64_t s = starts[i], m = lens[i];
        for (int64_t j = 0; j < m; ++j) out[k++] = s + j;
    }
    return k;
}

// 0..len-1 offsets of each flattened element within its range.
// Mirrors kindel_tpu.io.records.ragged_local_offsets. Returns -1 on any
// negative length (same allocation contract as ragged_indices64).
int64_t ragged_local64(const int64_t* lens, int64_t n, int64_t* out) {
    for (int64_t i = 0; i < n; ++i)
        if (lens[i] < 0) return -1;
    int64_t k = 0;
    for (int64_t i = 0; i < n; ++i) {
        const int64_t m = lens[i];
        for (int64_t j = 0; j < m; ++j) out[k++] = j;
    }
    return k;
}

// Fused CIGAR parse: n_ops[r] little-endian u32 words at byte starts[r] of
// `buf`; writes op codes (word & 0xF) and lengths (word >> 4) contiguously.
// Replaces the gather + view + two astype passes in _fields_from_offsets.
// Returns total ops, or -1 when any word lies outside the buffer.
int64_t parse_cigar(const uint8_t* buf, int64_t buf_len,
                    const int64_t* starts, const int64_t* n_ops,
                    int64_t n_reads, uint8_t* out_op, int64_t* out_len) {
    // whole-array pre-pass: out_op/out_len are sized by sum(n_ops), so with
    // mixed signs the positive entries alone would overrun them before a
    // per-iteration check ever saw the negative entry
    for (int64_t r = 0; r < n_reads; ++r)
        if (n_ops[r] < 0) return -1;
    int64_t k = 0;
    for (int64_t r = 0; r < n_reads; ++r) {
        int64_t off = starts[r];
        const int64_t m = n_ops[r];
        if (off < 0 || off + 4 * m > buf_len) return -1;
        for (int64_t j = 0; j < m; ++j, off += 4, ++k) {
            uint32_t w;
            std::memcpy(&w, buf + off, 4);
            out_op[k] = static_cast<uint8_t>(w & 0xF);
            out_len[k] = static_cast<int64_t>(w >> 4);
        }
    }
    return k;
}

// Fused SEQ decode: l_seq[r] bases packed two-per-byte (high nibble first)
// at byte starts[r]; maps nibbles through the 16-entry `nt16` table into
// contiguous ASCII. Replaces the ragged gather + nibble split + trim-mask
// passes in _fields_from_offsets. Returns total bases, or -1 out-of-bounds.
int64_t unpack_seq(const uint8_t* buf, int64_t buf_len,
                   const int64_t* starts, const int64_t* l_seq,
                   int64_t n_reads, const uint8_t* nt16, uint8_t* out) {
    // same allocation contract as parse_cigar: reject all-negative up front
    for (int64_t r = 0; r < n_reads; ++r)
        if (l_seq[r] < 0) return -1;
    int64_t k = 0;
    for (int64_t r = 0; r < n_reads; ++r) {
        const int64_t s = starts[r], m = l_seq[r];
        if (s < 0 || s + (m + 1) / 2 > buf_len) return -1;
        for (int64_t j = 0; j < m; ++j) {
            const uint8_t byte = buf[s + (j >> 1)];
            out[k++] = nt16[(j & 1) ? (byte & 0xF) : (byte >> 4)];
        }
    }
    return k;
}

// Fused M/=/X event expansion (the dominant event class): for op i and
// j < lens[i], position r_start[i]+j wraps Python-negative-index style
// (p in [-L, 0) maps to p+L) and is kept when 0 <= p < L[i]; the matching
// query base seq[q_abs[i]+j] maps through the 256-entry base_code table.
// Replaces two ragged_indices, two repeats, the wrap, the bounds mask and
// the code gather in events._fast_events. Returns events kept, or -1 when
// a query index leaves the seq buffer.
int64_t expand_match_events(const int64_t* r_start, const int64_t* q_abs,
                            const int64_t* lens, const int64_t* rid,
                            const int64_t* L, int64_t n_ops,
                            const uint8_t* seq, int64_t seq_len,
                            const uint8_t* base_code, int64_t* out_rid,
                            int64_t* out_pos, uint8_t* out_base) {
    // out buffers are sized by sum(lens): reject negatives before writing
    for (int64_t i = 0; i < n_ops; ++i)
        if (lens[i] < 0) return -1;
    int64_t k = 0;
    for (int64_t i = 0; i < n_ops; ++i) {
        const int64_t m = lens[i], ln = L[i], rd = rid[i];
        const int64_t rs = r_start[i], q0 = q_abs[i];
        if (m > 0 && (q0 < 0 || q0 + m > seq_len)) return -1;
        for (int64_t j = 0; j < m; ++j) {
            int64_t p = rs + j;
            if (p < 0) p += ln;
            if (p < 0 || p >= ln) continue;
            out_rid[k] = rd;
            out_pos[k] = p;
            out_base[k] = base_code[seq[q0 + j]];
            ++k;
        }
    }
    return k;
}

// Fused consensus-wire decode: expand the 2-bit base plane to ASCII
// through the 4-entry table and apply the exception bitmask (N/skip
// positions, MSB-first as numpy packbits writes it) in one pass.
// Replaces four strided numpy stores + unpackbits + where in
// call_jax.decode_fast. Caller guarantees plane holds ceil(L/4) bytes
// and exc ceil(L/8); returns -1 when the buffers are too short.
int64_t decode_plane(const uint8_t* plane, int64_t plane_len,
                     const uint8_t* exc, int64_t exc_len, int64_t L,
                     const uint8_t* base4, uint8_t n_char, uint8_t* out) {
    if (plane_len * 4 < L || exc_len * 8 < L) return -1;
    // byte-at-a-time LUT expansion (each packed byte -> 4 ASCII chars),
    // then a second pass that touches only NONZERO exception bytes —
    // exceptions (N / deletion-skip) are sparse on real pileups, so the
    // second pass is nearly free and the first is a straight table copy
    uint8_t lut[256][4];
    for (int v = 0; v < 256; ++v) {
        lut[v][0] = base4[(v >> 6) & 3];
        lut[v][1] = base4[(v >> 4) & 3];
        lut[v][2] = base4[(v >> 2) & 3];
        lut[v][3] = base4[v & 3];
    }
    const int64_t nb = L >> 2;
    for (int64_t j = 0; j < nb; ++j)
        std::memcpy(out + 4 * j, lut[plane[j]], 4);
    for (int64_t j = nb * 4; j < L; ++j)
        out[j] = base4[(plane[j >> 2] >> (6 - 2 * (j & 3))) & 3];
    const int64_t eb = (L + 7) / 8;
    for (int64_t k = 0; k < eb; ++k) {
        const uint8_t e = exc[k];
        if (!e) continue;
        const int64_t base = k * 8;
        for (int b = 0; b < 8; ++b) {
            if ((e >> (7 - b)) & 1) {
                const int64_t j = base + b;
                if (j < L) out[j] = n_char;
            }
        }
    }
    return L;
}

}  // extern "C"
