// Native BAM decode helpers for kindel-tpu.
//
// The only data-dependent sequential stage of L0 is walking BAM record
// boundaries (each record's offset depends on the previous block_size) —
// everything downstream is vectorized numpy / device code. This walk is done
// here in C++; a BGZF block inflater is included for large inputs where
// Python's gzip member loop becomes measurable.
//
// Exposed via ctypes (kindel_tpu/io/native.py). Build: make -C src/native

#include <cstdint>
#include <cstring>

#include <zlib.h>

extern "C" {

// Walk alignment-record boundaries of a decompressed BAM stream.
// `start` is the byte offset of the first record (after header+refs).
// Writes record-body offsets (start of refID field) into `out` (capacity
// `cap`). Returns the number of records, or -1 on malformed input / -2 if
// capacity is exhausted.
int64_t bam_scan_offsets(const uint8_t* data, int64_t len, int64_t start,
                         int64_t* out, int64_t cap) {
    int64_t off = start;
    int64_t n = 0;
    while (off + 4 <= len) {
        int32_t block_size;
        std::memcpy(&block_size, data + off, 4);
        if (block_size < 32 || off + 4 + block_size > len) return -1;
        if (n >= cap) return -2;
        out[n++] = off + 4;
        off += 4 + static_cast<int64_t>(block_size);
    }
    return n;
}

// Inflate a BGZF byte stream (concatenated gzip members with BC extra
// fields). Returns the decompressed size, or -1 on error / -2 if `out_cap`
// is too small. Each member's payload sits between the 18-byte BGZF header
// and the 8-byte CRC/ISIZE trailer; ISIZE gives the member's output size.
int64_t bgzf_inflate(const uint8_t* data, int64_t len, uint8_t* out,
                     int64_t out_cap) {
    int64_t off = 0;
    int64_t written = 0;
    while (off < len) {
        if (off + 18 > len) return -1;
        if (data[off] != 0x1f || data[off + 1] != 0x8b) return -1;
        // find BSIZE in the extra field (FLG.FEXTRA with "BC" subfield)
        if (!(data[off + 3] & 4)) return -1;
        uint16_t xlen;
        std::memcpy(&xlen, data + off + 10, 2);
        int64_t xoff = off + 12, xend = xoff + xlen;
        int64_t bsize = -1;
        while (xoff + 4 <= xend) {
            uint8_t si1 = data[xoff], si2 = data[xoff + 1];
            uint16_t slen;
            std::memcpy(&slen, data + xoff + 2, 2);
            if (si1 == 66 && si2 == 67 && slen == 2) {
                uint16_t bs;
                std::memcpy(&bs, data + xoff + 4, 2);
                bsize = static_cast<int64_t>(bs) + 1;
                break;
            }
            xoff += 4 + slen;
        }
        if (bsize < 26 || off + bsize > len) return -1;
        uint32_t isize;
        std::memcpy(&isize, data + off + bsize - 4, 4);
        if (written + isize > out_cap) return -2;

        z_stream zs;
        std::memset(&zs, 0, sizeof(zs));
        if (inflateInit2(&zs, -15) != Z_OK) return -1;
        zs.next_in = const_cast<uint8_t*>(data + off + 18);
        zs.avail_in = static_cast<uInt>(bsize - 26);
        zs.next_out = out + written;
        zs.avail_out = static_cast<uInt>(out_cap - written);
        int rc = inflate(&zs, Z_FINISH);
        uLong total_out = zs.total_out;
        inflateEnd(&zs);
        if (rc != Z_STREAM_END || total_out != isize) return -1;
        written += isize;
        off += bsize;
    }
    return written;
}

// Sum of ISIZE fields — exact decompressed size for preallocation.
int64_t bgzf_decompressed_size(const uint8_t* data, int64_t len) {
    int64_t off = 0;
    int64_t total = 0;
    while (off < len) {
        if (off + 18 > len || data[off] != 0x1f || data[off + 1] != 0x8b ||
            !(data[off + 3] & 4))
            return -1;
        uint16_t xlen;
        std::memcpy(&xlen, data + off + 10, 2);
        int64_t xoff = off + 12, xend = xoff + xlen;
        int64_t bsize = -1;
        while (xoff + 4 <= xend) {
            uint16_t slen;
            std::memcpy(&slen, data + xoff + 2, 2);
            if (data[xoff] == 66 && data[xoff + 1] == 67 && slen == 2) {
                uint16_t bs;
                std::memcpy(&bs, data + xoff + 4, 2);
                bsize = static_cast<int64_t>(bs) + 1;
                break;
            }
            xoff += 4 + slen;
        }
        if (bsize < 0 || off + bsize > len) return -1;
        uint32_t isize;
        std::memcpy(&isize, data + off + bsize - 4, 4);
        total += isize;
        off += bsize;
    }
    return total;
}

}  // extern "C"
