// Adversarial fuzz driver for the native decode kernels, built and run
// under AddressSanitizer/UBSan (make asan). Every exported function in
// bam_decode.cpp is fed truncated buffers, lying length fields, negative
// and overflowing sizes, and random corruption; the pass criterion is
// simply that the process exits 0 with no sanitizer report — each call
// must either succeed within bounds or return its documented error code.
//
// The Python-level accept/reject contract is pinned separately in
// tests/test_decode_fuzz.py; this driver exists because ctypes callers
// cannot see a heap-buffer-overflow that happens to land in mapped
// memory, and ASan can.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <random>
#include <vector>

extern "C" {
int64_t bam_scan_offsets(const uint8_t*, int64_t, int64_t, int64_t*, int64_t);
int64_t bgzf_inflate(const uint8_t*, int64_t, uint8_t*, int64_t);
int64_t bgzf_decompressed_size(const uint8_t*, int64_t);
int64_t ragged_indices64(const int64_t*, const int64_t*, int64_t, int64_t*);
int64_t ragged_local64(const int64_t*, int64_t, int64_t*);
int64_t parse_cigar(const uint8_t*, int64_t, const int64_t*, const int64_t*,
                    int64_t, uint8_t*, int64_t*);
int64_t unpack_seq(const uint8_t*, int64_t, const int64_t*, const int64_t*,
                   int64_t, const uint8_t*, uint8_t*);
int64_t expand_match_events(const int64_t*, const int64_t*, const int64_t*,
                            const int64_t*, const int64_t*, int64_t,
                            const uint8_t*, int64_t, const uint8_t*,
                            int64_t*, int64_t*, uint8_t*);
int64_t decode_plane(const uint8_t*, int64_t, const uint8_t*, int64_t,
                     int64_t, const uint8_t*, uint8_t, uint8_t*);
}

static std::mt19937_64 rng(2026);

static int64_t ri(int64_t lo, int64_t hi) {  // inclusive
    return lo + static_cast<int64_t>(rng() % static_cast<uint64_t>(hi - lo + 1));
}

// Exact-capacity allocations: an off-by-one write lands in ASan redzones.
struct Buf {
    std::vector<uint8_t> v;
    explicit Buf(int64_t n) : v(static_cast<size_t>(n)) {}
    uint8_t* p() { return v.data(); }
};

static void put32(std::vector<uint8_t>& b, size_t off, int32_t x) {
    std::memcpy(b.data() + off, &x, 4);
}

// --- bam_scan_offsets: lying block_size fields, truncations ---
static void fuzz_scan() {
    for (int iter = 0; iter < 2000; ++iter) {
        int64_t n = ri(0, 200);
        std::vector<uint8_t> data(static_cast<size_t>(n));
        for (auto& c : data) c = static_cast<uint8_t>(rng());
        // half the time, plant plausible-but-lying block sizes
        if (n >= 8 && (iter & 1)) {
            put32(data, 0, static_cast<int32_t>(ri(-40, n + 40)));
        }
        std::vector<int64_t> out(static_cast<size_t>(n / 36 + 8));
        bam_scan_offsets(data.data(), n, ri(0, n), out.data(),
                         static_cast<int64_t>(out.size()));
        // tiny capacity must hit the -2 path, never write past cap
        int64_t tiny[1];
        bam_scan_offsets(data.data(), n, 0, tiny, 1);
    }
}

// --- bgzf_inflate / bgzf_decompressed_size: corrupt framing ---
static void fuzz_bgzf() {
    // a syntactically BGZF-ish header with adversarial XLEN/BSIZE/ISIZE
    for (int iter = 0; iter < 2000; ++iter) {
        int64_t n = ri(0, 128);
        std::vector<uint8_t> d(static_cast<size_t>(n));
        for (auto& c : d) c = static_cast<uint8_t>(rng());
        if (n >= 18 && (iter % 3)) {
            d[0] = 0x1f; d[1] = 0x8b; d[2] = 8; d[3] = 4;
            uint16_t xlen = static_cast<uint16_t>(ri(0, 64));
            std::memcpy(d.data() + 10, &xlen, 2);
            if (n >= 18) {
                d[12] = 66; d[13] = 67;
                uint16_t slen = 2;
                std::memcpy(d.data() + 14, &slen, 2);
                uint16_t bs = static_cast<uint16_t>(ri(0, 200));
                std::memcpy(d.data() + 16, &bs, 2);
            }
        }
        bgzf_decompressed_size(d.data(), n);
        Buf out(256);
        bgzf_inflate(d.data(), n, out.p(), 256);
        // zero-capacity output: ISIZE lies must be caught before writes
        bgzf_inflate(d.data(), n, out.p(), 0);
    }
}

// --- ragged kernels: negative/overflow lengths, exact capacity ---
static void fuzz_ragged() {
    for (int iter = 0; iter < 2000; ++iter) {
        int64_t n = ri(0, 64);
        std::vector<int64_t> starts(static_cast<size_t>(n)),
            lens(static_cast<size_t>(n));
        int64_t total = 0;
        bool neg = false;
        for (int64_t i = 0; i < n; ++i) {
            starts[static_cast<size_t>(i)] = ri(-100, 100);
            int64_t l = ri(iter % 4 ? 0 : -8, 16);  // negatives 1 in 4 runs
            lens[static_cast<size_t>(i)] = l;
            if (l < 0) neg = true; else total += l;
        }
        // capacity sized exactly as the Python callers size it: sum of
        // lens when all non-negative; with negatives present the call must
        // return -1 BEFORE writing anything, so even a zero-sized buffer
        // is legal
        std::vector<int64_t> out(static_cast<size_t>(neg ? 0 : total));
        int64_t rc = ragged_indices64(starts.data(), lens.data(), n,
                                      out.data());
        if (neg && rc != -1) { std::fprintf(stderr, "neg accept\n"); __builtin_trap(); }
        std::vector<int64_t> out2(static_cast<size_t>(neg ? 0 : total));
        rc = ragged_local64(lens.data(), n, out2.data());
        if (neg && rc != -1) { std::fprintf(stderr, "neg accept\n"); __builtin_trap(); }
    }
}

// --- parse_cigar / unpack_seq: out-of-buffer starts, lying counts ---
static void fuzz_parse() {
    for (int iter = 0; iter < 2000; ++iter) {
        int64_t blen = ri(0, 256);
        std::vector<uint8_t> buf(static_cast<size_t>(blen));
        for (auto& c : buf) c = static_cast<uint8_t>(rng());
        int64_t n = ri(0, 16);
        std::vector<int64_t> starts(static_cast<size_t>(n)),
            counts(static_cast<size_t>(n));
        int64_t total = 0;
        bool neg = false;
        for (int64_t i = 0; i < n; ++i) {
            starts[static_cast<size_t>(i)] = ri(-16, blen + 16);
            int64_t c = ri(iter % 4 ? 0 : -4, 12);
            counts[static_cast<size_t>(i)] = c;
            if (c < 0) neg = true; else total += c;
        }
        std::vector<uint8_t> op(static_cast<size_t>(neg ? 0 : total));
        std::vector<int64_t> ln(static_cast<size_t>(neg ? 0 : total));
        int64_t rc = parse_cigar(buf.data(), blen, starts.data(),
                                 counts.data(), n, op.data(), ln.data());
        if (neg && rc != -1) { std::fprintf(stderr, "neg accept\n"); __builtin_trap(); }
        uint8_t nt16[16];
        for (int i = 0; i < 16; ++i) nt16[i] = static_cast<uint8_t>('A' + i);
        std::vector<uint8_t> seq_out(static_cast<size_t>(neg ? 0 : total));
        rc = unpack_seq(buf.data(), blen, starts.data(), counts.data(), n,
                        nt16, seq_out.data());
        if (neg && rc != -1) { std::fprintf(stderr, "neg accept\n"); __builtin_trap(); }
    }
}

// --- expand_match_events: wrap positions, out-of-range query offsets ---
static void fuzz_expand() {
    for (int iter = 0; iter < 2000; ++iter) {
        int64_t seq_len = ri(0, 128);
        std::vector<uint8_t> seq(static_cast<size_t>(seq_len));
        for (auto& c : seq) c = static_cast<uint8_t>(rng());
        uint8_t code[256];
        for (int i = 0; i < 256; ++i) code[i] = static_cast<uint8_t>(i & 7);
        int64_t n = ri(0, 16);
        std::vector<int64_t> rs(static_cast<size_t>(n)),
            qa(static_cast<size_t>(n)), lens(static_cast<size_t>(n)),
            rid(static_cast<size_t>(n)), L(static_cast<size_t>(n));
        int64_t total = 0;
        bool neg = false;
        for (int64_t i = 0; i < n; ++i) {
            rs[static_cast<size_t>(i)] = ri(-300, 300);
            qa[static_cast<size_t>(i)] = ri(-8, seq_len + 8);
            int64_t l = ri(iter % 4 ? 0 : -4, 24);
            lens[static_cast<size_t>(i)] = l;
            rid[static_cast<size_t>(i)] = ri(0, 3);
            L[static_cast<size_t>(i)] = ri(0, 200);
            if (l < 0) neg = true; else total += l;
        }
        std::vector<int64_t> orid(static_cast<size_t>(neg ? 0 : total)),
            opos(static_cast<size_t>(neg ? 0 : total));
        std::vector<uint8_t> ob(static_cast<size_t>(neg ? 0 : total));
        int64_t rc = expand_match_events(
            rs.data(), qa.data(), lens.data(), rid.data(), L.data(), n,
            seq.data(), seq_len, code, orid.data(), opos.data(), ob.data());
        if (neg && rc != -1) { std::fprintf(stderr, "neg accept\n"); __builtin_trap(); }
    }
}

// --- decode_plane: short wire buffers, lying L, exact-capacity output ---
static void fuzz_decode_plane() {
    uint8_t base4[4] = {'A', 'C', 'G', 'T'};
    for (int iter = 0; iter < 2000; ++iter) {
        int64_t plane_len = ri(0, 64), exc_len = ri(0, 64);
        std::vector<uint8_t> plane(static_cast<size_t>(plane_len)),
            exc(static_cast<size_t>(exc_len));
        for (auto& c : plane) c = static_cast<uint8_t>(rng());
        for (auto& c : exc) c = static_cast<uint8_t>(rng());
        int64_t L = ri(0, 300);  // often lies past the buffers
        std::vector<uint8_t> out(static_cast<size_t>(L));
        int64_t rc = decode_plane(plane.data(), plane_len, exc.data(),
                                  exc_len, L, base4, 'N', out.data());
        const bool fits = plane_len * 4 >= L && exc_len * 8 >= L;
        if (fits != (rc == L)) { std::fprintf(stderr, "plane gate\n"); __builtin_trap(); }
    }
}

int main() {
    fuzz_scan();
    fuzz_bgzf();
    fuzz_ragged();
    fuzz_parse();
    fuzz_expand();
    fuzz_decode_plane();
    std::puts("fuzz_driver: ok");
    return 0;
}
