"""Benchmark: end-to-end BAM → consensus FASTA throughput.

Headline metric (BASELINE.md): consensus Mbases/s on the bacterial-scale
BAM (6.1 Mb reference, tests/data_minimap2_bact/bact.tiny.bam). The
reference implementation measures 0.069 Mbases/s end-to-end on one CPU core
(88.3 s); vs_baseline is the speedup over that.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "backend": ...}

Hermeticity contract (round-1 postmortem, VERDICT.md "Next round" item 1):
this parent process NEVER imports jax. The measured run happens in a
watchdog-timed child; if the tunneled TPU relay is dead or its backend
fails to initialize, the benchmark reruns in a CPU child with the
accelerator hook scrubbed and the JSON line is labeled
``"backend": "cpu-fallback"`` with the TPU error attached — one environment
flap must never void the round's perf evidence.
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent
sys.path.insert(0, str(REPO))

BACT_BAM = Path(
    os.environ.get(
        "KINDEL_TPU_BENCH_BAM",
        "/root/reference/tests/data_minimap2_bact/bact.tiny.bam",
    )
)
BASELINE_MBASES_PER_S = 0.069  # reference end-to-end, 1 CPU core (SURVEY §6)

#: first compiles ~20-40 s each (the adaptive slab autotune measures 3-5
#: configs on a cold cache, but stops expanding past TUNE_BUDGET_S) +
#: tunneled transfers; must stay under the relay watcher's 900 s kill
#: window minus the 300 s CPU child
TPU_ATTEMPT_TIMEOUT_S = 560.0
#: wall budget for the autotune phase: whatever configs are measured by
#: this point decide the pick, so a cold cache can never starve the
#: timed trials of their share of TPU_ATTEMPT_TIMEOUT_S
TUNE_BUDGET_S = 300.0
CPU_ATTEMPT_TIMEOUT_S = 300.0
#: how long to wait for the relay to answer before falling back — the
#: round-2 verdict flagged a single 30 s probe as throwing away whole
#: uptime windows; the driver's end-of-round run deserves a longer grace
RELAY_WAIT_S = float(os.environ.get("KINDEL_TPU_BENCH_RELAY_WAIT_S", "90"))
#: TPU attempts before CPU fallback (a crash retries; a full-timeout
#: hang does not — a second identical hang would double the stall)
TPU_ATTEMPTS = max(1, int(os.environ.get("KINDEL_TPU_BENCH_TPU_ATTEMPTS", "2")))


def _synthesize_bam(path: Path, ref_len: int = 6_097_032,
                    n_reads: int = 12_000, read_len: int = 140):
    """Fallback workload if the reference corpus is unavailable: a BGZF BAM
    with the same scale (6.1 Mb ref, ~1.7 M aligned bases)."""
    import gzip
    import struct

    import numpy as np

    rng = np.random.default_rng(0)
    name = b"SYNTH1\x00"
    header_text = f"@SQ\tSN:SYNTH1\tLN:{ref_len}\n".encode()
    hdr = b"BAM\x01" + struct.pack("<i", len(header_text)) + header_text
    hdr += struct.pack("<i", 1)
    hdr += struct.pack("<i", len(name)) + name + struct.pack("<i", ref_len)
    out = [hdr]
    bases = np.frombuffer(b"ACGT", dtype=np.uint8)
    code = {65: 1, 67: 2, 71: 4, 84: 8}
    for _ in range(n_reads):
        pos = int(rng.integers(0, ref_len - read_len))
        seq_ascii = bases[rng.integers(0, 4, size=read_len)]
        nib = np.array([code[b] for b in seq_ascii], dtype=np.uint8)
        packed = bytearray()
        for i in range(0, read_len, 2):
            hi = nib[i] << 4
            lo = nib[i + 1] if i + 1 < read_len else 0
            packed.append(hi | lo)
        rname = b"r\x00"
        cigar = struct.pack("<I", (read_len << 4) | 0)
        body = struct.pack(
            "<iiBBHHHiiii", 0, pos, len(rname), 60, 0, 1, 0,
            read_len, -1, -1, 0,
        )
        body += rname + cigar + bytes(packed) + b"\xff" * read_len
        out.append(struct.pack("<i", len(body)) + body)
    raw = b"".join(out)
    path.write_bytes(gzip.compress(raw, 1))


def _max_ref_len(bam: Path) -> int:
    """Longest reference length, from the BAM header alone (no record
    decode — the autotune clamp only needs contig scale, and decoding the
    whole file an extra time to learn it measurably skews 1-core runs).
    BGZF is gzip-compatible and gzip.open streams, so only the first
    block(s) are ever decompressed. Returns 0 on anything unexpected
    (caller treats 0 as "no clamp information")."""
    import gzip
    import struct

    try:
        with open(bam, "rb") as raw:
            magic = raw.read(2)
        opener = gzip.open if magic == b"\x1f\x8b" else open
        with opener(bam, "rb") as fh:
            if fh.read(4) != b"BAM\x01":
                return 0
            l_text = struct.unpack("<i", fh.read(4))[0]
            fh.read(l_text)
            n_ref = struct.unpack("<i", fh.read(4))[0]
            longest = 0
            for _ in range(n_ref):
                l_name = struct.unpack("<i", fh.read(4))[0]
                fh.read(l_name)
                longest = max(longest, struct.unpack("<i", fh.read(4))[0])
            return longest
    except Exception:
        return 0


def _resilience_counts(snapshot: dict) -> dict:
    """Sum the process-global resilience counters across their label
    children: {"retry_total", "degrade_total", "breaker_trips",
    "numpy_fallbacks"} — all 0 on a clean run."""

    def total(prefix: str) -> int:
        return sum(
            int(v) for k, v in snapshot.items()
            if k == prefix or k.startswith(prefix + "{")
        )

    return {
        "retry_total": total("kindel_retry_total"),
        "degrade_total": total("kindel_degrade_total"),
        "breaker_trips": total("kindel_breaker_trips_total"),
        "numpy_fallbacks": total("kindel_fallback_numpy_total"),
    }


def _fleet_counts(snapshot: dict) -> dict:
    """Fleet posture (kindel_tpu.fleet): evictions / failovers / hedges
    / drained / replayed / restarts — all 0 outside fleet serving, and
    a round that hit its number while evicting replicas must say so,
    same rationale as the resilience object."""
    return {
        "evictions": int(snapshot.get("kindel_fleet_evictions_total", 0)),
        "failovers": int(snapshot.get("kindel_fleet_failovers_total", 0)),
        "hedges": int(snapshot.get("kindel_fleet_hedges_total", 0)),
        "drained": int(snapshot.get(
            "kindel_fleet_drained_requests_total", 0
        )),
        "replays": int(snapshot.get(
            "kindel_fleet_replayed_requests_total", 0
        )),
        "restarts": int(snapshot.get("kindel_fleet_restarts_total", 0)),
    }


def _rpc_counts(snapshot: dict) -> dict:
    """Wire posture (kindel_tpu.fleet.rpc): RPC exchanges by outcome,
    client call p50/p99, transport resubmissions, server-side dedupe
    hits, and autoscale events — all 0 outside process-fleet serving.
    Same rationale as the fleet object: a round that hit its number by
    resubmitting over a flaky wire must say so."""

    def label_total(prefix: str, **match) -> int:
        out = 0
        for k, v in snapshot.items():
            if not (k == prefix or k.startswith(prefix + "{")):
                continue
            if match and not all(
                f'{mk}="{mv}"' in k for mk, mv in match.items()
            ):
                continue
            if isinstance(v, (int, float)):
                out += int(v)
        return out

    seconds = snapshot.get("kindel_rpc_call_seconds", {})
    if not isinstance(seconds, dict):
        seconds = {}
    return {
        "calls": label_total("kindel_rpc_calls_total"),
        "call_p50_ms": round(float(seconds.get("p50", 0.0)) * 1e3, 2),
        "call_p99_ms": round(float(seconds.get("p99", 0.0)) * 1e3, 2),
        "retries": label_total(
            "kindel_retry_total", site="rpc.call", outcome="retried"
        ),
        "dedup_hits": int(
            snapshot.get("kindel_rpc_dedup_hits_total", 0)
        ),
        "scale_up": label_total(
            "kindel_fleet_scale_events_total", direction="up"
        ),
        "scale_down": label_total(
            "kindel_fleet_scale_events_total", direction="down"
        ),
        "respawns": int(snapshot.get("kindel_fleet_respawns_total", 0)),
    }


def _run_benchmark() -> dict:
    """The measured pipeline. Runs only in a child process (jax imported
    here, never in the parent)."""
    bam = BACT_BAM
    if not bam.exists():
        bam = Path("/tmp/kindel_tpu_synth.bam")
        if not bam.exists():
            _synthesize_bam(bam)

    import jax

    from kindel_tpu import tune as tunelib
    from kindel_tpu.events import extract_events
    from kindel_tpu.io import load_alignment
    from kindel_tpu.call_jax import call_consensus_fused
    from kindel_tpu.obs import runtime as obs_runtime
    from kindel_tpu.obs import trace as obs_trace
    from kindel_tpu.obs.metrics import default_registry
    from kindel_tpu.pileup import build_pileup  # noqa: F401 (import check)
    from kindel_tpu.utils.profiling import (
        disable_profiling,
        enable_profiling,
        maybe_phase,
    )

    # compile accounting from the first warmup dispatch onward — the
    # emitted line attributes cold-start (tune/warm) vs steady-state cost
    obs_runtime.install()

    def one_pass(slabs: int) -> int:
        with maybe_phase("decode"):
            batch = load_alignment(bam)
        with maybe_phase("event extraction"):
            ev = extract_events(batch)
        total = 0
        cfg = tunelib.TuningConfig(n_slabs=slabs)
        with maybe_phase("device call+assemble"):
            for rid in ev.present_ref_ids:
                res, _dmin, _dmax = call_consensus_fused(
                    ev, rid, build_changes=False, tuning=cfg
                )
                total += int(ev.ref_lens[rid])
                assert len(res.sequence) > 0
        return total

    # Slab autotune via kindel_tpu.tune (the search was lifted out of
    # this file into the library in PR 2): the pipelined slab sweep
    # overlaps wire with compute, but on a high-latency tunneled link the
    # extra per-slab dispatches could cost more than the overlap saves —
    # which way it goes is a property of THIS host/link, so it is
    # measured once, persisted in the tune store, and every later run
    # (this bench, the CLI, serve) starts hot: a warm store skips the
    # measure loop entirely (tune_source: "cache"). An explicit
    # KINDEL_TPU_SLABS pins the config ("pinned"); the per-contig clamp
    # makes all configs identical on small-contig inputs ("default").
    # The slab count flows EXPLICITLY through TuningConfig — the search
    # mutates no env, so an exception mid-probe cannot leak state
    # (the old in-file search left KINDEL_TPU_SLABS set on exception).
    # Header-only scan: the clamp needs contig scale, not a full decode
    # (an over-estimate from a read-less contig only times configs that
    # collapse to the same effective count — correctness is unaffected).
    max_contig = _max_ref_len(bam)
    if max_contig == 0:  # non-BAM / unreadable header: decode-probe fallback
        probe = extract_events(load_alignment(bam))
        max_contig = max(
            (int(probe.ref_lens[r]) for r in probe.present_ref_ids), default=0
        )
    clamp = tunelib.slab_clamp(max_contig)
    backend = jax.default_backend()
    store_key = tunelib.store_key(backend, max_contig)
    tune: dict[int, float] = {}
    t_tune = time.perf_counter()
    if os.environ.get("KINDEL_TPU_SLABS"):
        pinned, _src = tunelib.resolve_slabs(
            backend=backend, max_contig=max_contig, consult_store=False
        )
        chosen = min(max(1, pinned), clamp)
        tune_source = "pinned"
        one_pass(chosen)  # warmup/compile
    elif clamp <= 1:
        chosen = 1
        tune_source = "default"
        one_pass(1)
    else:
        cached = tunelib.lookup(store_key)
        if cached and isinstance(cached.get("n_slabs"), int):
            # warm store: 0 s in the measure loop — warmup/compile only
            chosen = min(max(1, cached["n_slabs"]), clamp)
            tune_source = "cache"
            one_pass(chosen)
        else:
            chosen, tune = tunelib.measured_slabs(
                one_pass, clamp, TUNE_BUDGET_S
            )
            tune_source = "measured"
            tunelib.record(
                store_key,
                {
                    "n_slabs": chosen,
                    "timings_s": {
                        str(k): round(v, 4) for k, v in tune.items()
                    },
                    "tune_wall_s": round(time.perf_counter() - t_tune, 3),
                    "bam_path": str(bam),
                },
            )
    tune_wall = time.perf_counter() - t_tune

    # timed: full pipeline — decode, event extraction, device reduce+call,
    # host assembly (jit cache warm, as in steady-state batch processing).
    # Best of 3 trials: single-shot walls swing ±40% on shared hosts /
    # contended tunnels, and the recorded number must be comparable
    # across rounds. Trials run under the span tracer + phase timer so
    # the emitted line carries stage attribution (obs.spans/obs.phases),
    # not just end-to-end wall; the in-memory exporter adds one list
    # append per span (~10 spans/pass) — noise next to the measured work.
    compiles_warm, compile_wall_warm = obs_runtime.compile_totals()
    exporter = obs_trace.ListExporter()
    obs_trace.enable_tracing(exporter=exporter)
    timer = enable_profiling()
    walls = []
    ingest_before = {
        k: v for k, v in default_registry().snapshot().items()
        if k.startswith("kindel_ingest_")
    }
    trial_transfers = []
    try:
        for _ in range(3):
            h2d_c, d2h_c = obs_runtime.transfer_counters()
            tr0 = (int(h2d_c.value), int(d2h_c.value))
            t0 = time.perf_counter()
            total_bases = one_pass(chosen)
            walls.append(time.perf_counter() - t0)
            trial_transfers.append({
                "h2d_bytes": int(h2d_c.value) - tr0[0],
                "d2h_bytes": int(d2h_c.value) - tr0[1],
            })
    finally:
        disable_profiling()
        obs_trace.disable_tracing()
    spans: dict[str, dict] = {}
    for rec in exporter.records:
        agg = spans.setdefault(rec["name"], {"count": 0, "wall_s": 0.0})
        agg["count"] += 1
        agg["wall_s"] += rec["duration_s"]
    compiles_total, compile_wall_total = obs_runtime.compile_totals()

    # host-ingest attribution over the 3 timed trials (counter deltas,
    # same convention as compiles_during_trials): the wall split tells a
    # host-bound round (inflate/scan/expand dominating) from a
    # device-bound one, and the provenance says WHERE the worker count
    # came from — the same story tune_source tells for slabs
    from kindel_tpu.io import inflate as ingest_inflate

    ingest_workers, ingest_source = tunelib.resolve_ingest_workers()
    ingest_mode, ingest_mode_source = tunelib.resolve_ingest_mode()
    ingest_after = {
        k: v for k, v in default_registry().snapshot().items()
        if k.startswith("kindel_ingest_")
    }

    def ingest_delta(name: str) -> float:
        key = f"kindel_ingest_{name}"
        return ingest_after.get(key, 0) - ingest_before.get(key, 0)

    ingest = {
        "workers": ingest_workers,
        "workers_source": ingest_source,
        # mode provenance mirrors tune_source: the "ingest no longer
        # host-bound" claim is attributable to a mode + its origin, and
        # the device wall split below accounts the moved work
        "mode": ingest_mode,
        "mode_source": ingest_mode_source,
        "pool_workers_used": ingest_inflate.pool_workers(),
        "inflate_s": round(ingest_delta("inflate_seconds_total"), 3),
        "scan_s": round(ingest_delta("scan_seconds_total"), 3),
        "expand_s": round(ingest_delta("expand_seconds_total"), 3),
        "read_s": round(ingest_delta("read_seconds_total"), 3),
        "stall_s": round(ingest_delta("stall_seconds_total"), 3),
        "scan_device_s": round(ingest_delta("scan_device_seconds_total"), 3),
        "expand_device_s": round(
            ingest_delta("expand_device_seconds_total"), 3
        ),
        "upload_bytes": int(ingest_delta("upload_bytes_total")),
        "members": int(ingest_delta("members_total")),
        "bytes_in": int(ingest_delta("bytes_in_total")),
        "bytes_out": int(ingest_delta("bytes_out_total")),
    }

    from kindel_tpu import aot as aotlib

    metrics_snapshot = default_registry().snapshot()
    mbases_per_s = total_bases / min(walls) / 1e6
    result = {
        "metric": "consensus_throughput_bacterial",
        "value": round(mbases_per_s, 3),
        "unit": "Mbases/s",
        "vs_baseline": round(mbases_per_s / BASELINE_MBASES_PER_S, 1),
        "backend": jax.default_backend(),
        "slabs": chosen,
        "tune_source": tune_source,
        "tune_wall_s": round(tune_wall, 3),
        # AOT executable provenance (kindel_tpu.aot), mirroring
        # tune_source: did the device programs this run dispatched load
        # from the serialized-executable store, compile fresh, or run
        # with the store disabled? A perf claim that ran warm must say so.
        "aot": aotlib.provenance(),
        # fat-dispatch posture: resolved lane-coalescing width + how many
        # ready flushes actually merged (nonzero only under serve load)
        "dispatch": {
            "lane_coalesce": tunelib.resolve_lane_coalesce()[0],
            "coalesced_flushes": int(metrics_snapshot.get(
                "kindel_dispatch_coalesced_flushes_total", 0
            )),
            "coalesced_launches": int(metrics_snapshot.get(
                "kindel_dispatch_coalesced_launches_total", 0
            )),
        },
        # host-ingest posture (kindel_tpu.io.inflate): wall split +
        # worker-count provenance, mirroring tune_source for slabs
        "ingest": ingest,
        # transfer posture (ISSUE 13): h2d/d2h bytes per timed trial
        # from the declared download/upload sites, plus the resolved
        # emission mode — the "d2h collapses under device emit" and
        # "paged h2d is delta-only" claims are measured numbers here
        # and per-mode in the paged scenario's `transfers` objects,
        # never a story
        "transfers": {
            "emit_mode": tunelib.resolve_emit_mode()[0],
            "emit_mode_source": tunelib.resolve_emit_mode()[1],
            "per_trial": trial_transfers,
        },
        "trials": [round(w, 3) for w in walls],
        # contention context (VERDICT r4 weak 1): a cross-round comparison
        # is meaningless without knowing how busy the host was
        "loadavg_1m": round(os.getloadavg()[0], 2),
        "ncpu": os.cpu_count(),
        # stage attribution (kindel_tpu.obs): per-phase wall + span
        # summary over the 3 timed trials, compile cost split warm vs
        # trial, and the process-global metric snapshot (transfer bytes,
        # tune provenance, stream chunks)
        "obs": {
            "phases": {
                k: round(v, 3) for k, v in timer.totals().items()
            },
            "spans": {
                k: {"count": v["count"], "wall_s": round(v["wall_s"], 3)}
                for k, v in sorted(spans.items())
            },
            "compiles": compiles_total,
            "compile_wall_s": round(compile_wall_total, 3),
            "compiles_during_trials": compiles_total - compiles_warm,
            "compile_wall_during_trials_s": round(
                compile_wall_total - compile_wall_warm, 3
            ),
            "metrics": {
                k: v for k, v in sorted(default_registry().snapshot().items())
                if not k.startswith("kindel_jax_compile_seconds")
            },
        },
        # resilience posture (kindel_tpu.resilience): a round that only
        # hit its number by retrying/degrading is not comparable to a
        # clean one — the trajectory must be able to tell them apart
        "resilience": _resilience_counts(default_registry().snapshot()),
        # fleet posture (kindel_tpu.fleet): replica evictions/failovers/
        # drains during the round (nonzero only under fleet serve load)
        "fleet": _fleet_counts(default_registry().snapshot()),
        # wire posture (kindel_tpu.fleet.rpc): RPC call p50/p99,
        # resubmissions, dedupe hits, autoscale events (nonzero only
        # under process-fleet serve load — KINDEL_TPU_BENCH_SERVE=procs:N)
        "rpc": _rpc_counts(default_registry().snapshot()),
    }
    if tune:
        result["tune_s"] = {str(k): round(v, 3) for k, v in tune.items()}

    # static-analysis posture (kindel_tpu.analysis): rule count, finding
    # count, baseline state, and wall seconds, so the lint stage's cost
    # is tracked like every other stage — and a round that ran with new
    # findings outstanding says so in its provenance. Failure never
    # voids the headline metric.
    try:
        from kindel_tpu.analysis import lint_provenance

        result["lint"] = lint_provenance()
    except Exception as e:  # noqa: BLE001
        result["lint"] = {"error": repr(e)}

    # Perf-regression posture (kindel_tpu.obs.perfgate): where does this
    # round's headline number stand against the committed bench history
    # for the same (backend, series)? The verdict rides along in the
    # result line so a regressed round is self-describing — the gate
    # itself (`kindel perf --gate`) stays a separate CI stage. Failure
    # never voids the headline metric.
    try:
        from kindel_tpu.obs import perfgate

        result["perfgate"] = perfgate.provenance(REPO, result)
    except Exception as e:  # noqa: BLE001
        result["perfgate"] = {"error": repr(e)}

    # Shape-diverse serve scenario (kindel_tpu.ragged): the ROADMAP's
    # multi-sample regime — mixed contig/read lengths, some multi-ref
    # payloads — run through BOTH batch modes; the `ragged` object
    # reports per-mode occupancy, pad waste, superbatch count, and
    # jit-cache entries, with byte-identity asserted between modes.
    # Default-on for CPU children (seconds of wall); on an accelerator
    # the mode-pair's compile set competes with the relay watchdog, so
    # it needs the explicit KINDEL_TPU_BENCH_RAGGED=1 opt-in
    # (KINDEL_TPU_BENCH_RAGGED=0 disables everywhere). Failure never
    # voids the headline metric.
    ragged_pin = os.environ.get("KINDEL_TPU_BENCH_RAGGED")
    want_ragged = (
        jax.default_backend() == "cpu" if ragged_pin is None
        else ragged_pin not in ("", "0")
    )
    if want_ragged:
        try:
            from benchmarks.ragged_load import run_shape_diverse

            result["ragged"] = run_shape_diverse(requests=10)
        except Exception as e:  # noqa: BLE001
            result["ragged"] = {"error": repr(e)}

    # Open-loop continuous-superbatching scenario (kindel_tpu.paged):
    # the straggler-heavy + repeated-reference arrival mix run through
    # lanes/ragged/paged with byte-identity asserted; the `paged`
    # object records per-mode occupancy/latency plus paged residency,
    # retire p50/p99, and the panel-cache hit rate. Same gating rule as
    # the ragged scenario (KINDEL_TPU_BENCH_PAGED overrides; default-on
    # only for CPU children). Failure never voids the headline metric.
    paged_pin = os.environ.get("KINDEL_TPU_BENCH_PAGED")
    want_paged = (
        jax.default_backend() == "cpu" if paged_pin is None
        else paged_pin not in ("", "0")
    )
    if want_paged:
        try:
            from benchmarks.paged_load import run_open_loop

            result["paged"] = run_open_loop(requests=15)
        except Exception as e:  # noqa: BLE001
            result["paged"] = {"error": repr(e)}

    # Streaming-consensus scenario (kindel_tpu.sessions): S live
    # /v1/stream sessions fed by an open-loop appender, with a
    # mid-stream journal respawn; the `stream` object records update
    # latency p50/p99, emits-per-append, d2h bytes per published
    # update, and the replay count, with byte-identity against the
    # one-shot oracle asserted per session (`converged`). Same gating
    # rule as the ragged scenario (KINDEL_TPU_BENCH_STREAM overrides;
    # default-on only for CPU children). Failure never voids the
    # headline metric.
    stream_pin = os.environ.get("KINDEL_TPU_BENCH_STREAM")
    want_stream = (
        jax.default_backend() == "cpu" if stream_pin is None
        else stream_pin not in ("", "0")
    )
    if want_stream:
        try:
            from benchmarks.stream_load import run_stream_load

            result["stream"] = run_stream_load(
                sessions=3, appends_per_session=4
            )
        except Exception as e:  # noqa: BLE001
            result["stream"] = {"error": repr(e)}

    # Mesh sweep (kindel_tpu.parallel.meshexec): the shape-diverse
    # request set served once per mesh width dp∈{1,2,4,8} (clamped to
    # the visible devices) with byte-identity asserted across widths;
    # the `mesh` object reports per-dp wall/occupancy/launch/transfer
    # deltas (MULTICHIP_r06 records one run). Same gating rule as the
    # ragged scenario (KINDEL_TPU_BENCH_MESH overrides; default-on only
    # for CPU children). Failure never voids the headline metric.
    mesh_pin = os.environ.get("KINDEL_TPU_BENCH_MESH")
    want_mesh = (
        jax.default_backend() == "cpu" if mesh_pin is None
        else mesh_pin not in ("", "0")
    )
    if want_mesh:
        try:
            from benchmarks.mesh_sweep import run_mesh_sweep

            result["mesh"] = run_mesh_sweep(requests=8)
        except Exception as e:  # noqa: BLE001
            result["mesh"] = {"error": repr(e)}

    # Pod sweep (kindel_tpu.parallel.meshexec, DESIGN.md §27): the pod
    # cohort through all three tiers at dp × procs — degraded
    # single-process pod plans plus an actual localhost 2-process JAX
    # group — identity asserted against the dp=1 oracle; the `pod`
    # object reports per-config wall and the cross-process allgather
    # byte tax (MULTICHIP_r07 records one run). Same gating rule as
    # the mesh sweep (KINDEL_TPU_BENCH_POD overrides; default-on only
    # for CPU children). Failure never voids the headline metric.
    pod_pin = os.environ.get("KINDEL_TPU_BENCH_POD")
    want_pod = (
        jax.default_backend() == "cpu" if pod_pin is None
        else pod_pin not in ("", "0")
    )
    if want_pod:
        try:
            from benchmarks.pod_sweep import run_pod_sweep

            result["pod"] = run_pod_sweep()
        except Exception as e:  # noqa: BLE001
            result["pod"] = {"error": repr(e)}

    # Optional serving metrics (KINDEL_TPU_BENCH_SERVE=1): a small
    # closed-loop load run against the in-process service, so rounds can
    # track online throughput / p99 latency / batch occupancy alongside
    # the offline headline number. Opt-in because it adds ~seconds of
    # wall and its own kernel-shape compiles; failure never voids the
    # headline metric.
    bench_serve = os.environ.get("KINDEL_TPU_BENCH_SERVE")
    if bench_serve:
        try:
            from benchmarks.serve_load import run_load

            # KINDEL_TPU_BENCH_SERVE=N with N>1 runs the loop against a
            # supervised N-replica fleet (kindel_tpu.fleet);
            # KINDEL_TPU_BENCH_SERVE=procs:N runs it against N replica
            # PROCESSES over RPC (kindel_tpu.fleet.procreplica — the
            # serve report then carries the `rpc` object); any other
            # truthy value keeps the original single-service loop
            serve_procs = 0
            if bench_serve.startswith("procs:"):
                try:
                    serve_procs = int(bench_serve.split(":", 1)[1])
                except ValueError:
                    serve_procs = 2
                serve_replicas = 1
            else:
                try:
                    serve_replicas = int(bench_serve)
                except ValueError:
                    serve_replicas = 1
            result["serve"] = run_load(
                clients=4, requests_per_client=8,
                replicas=serve_replicas if serve_replicas > 1 else 0,
                procs=serve_procs,
            )
        except Exception as e:  # noqa: BLE001
            result["serve"] = {"error": repr(e)}
    return result


def _parse_child_json(stdout: str) -> dict | None:
    for line in reversed(stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def _tail(text: str, n: int = 800) -> str:
    return text[-n:] if text else ""


def main() -> None:
    import _hermetic as hz

    errors: list[str] = []
    argv = [sys.executable, str(REPO / "bench.py")]
    child_marker = {"KINDEL_TPU_BENCH_CHILD": "1"}

    # Accelerator attempts: each re-probes the relay first (cheap when
    # it is down), retries crashes, and does not retry a full-timeout
    # hang (a second identical hang would just double the stall).
    if hz.pool_advertised():
        for attempt in range(TPU_ATTEMPTS):
            if not hz.wait_for_relay(RELAY_WAIT_S):
                errors.append(
                    f"accelerator relay dead (no listener on "
                    f"{hz.RELAY_PORTS} after {RELAY_WAIT_S:.0f}s, "
                    f"attempt {attempt + 1})"
                )
                print(errors[-1], file=sys.stderr)
                break
            if os.environ.get("KINDEL_TPU_BENCH_SKIP_PJRT_PROBE"):
                ok, note = True, "probe skipped (caller pre-flighted)"
            else:
                ok, note = hz.pjrt_probe()
            if not ok:
                # Ports open but the PJRT client cannot initialize — the
                # full bench child would hang to its 420 s watchdog on the
                # same init path, so record the sharper evidence and stop.
                errors.append(note)
                print(errors[-1], file=sys.stderr)
                break
            env = hz.accelerator_env()
            env.update(child_marker)
            proc = hz.run_child(argv, env, TPU_ATTEMPT_TIMEOUT_S)
            result = _parse_child_json(proc.stdout)
            if (
                proc.returncode == 0
                and result is not None
                and result.get("backend") != "cpu"
            ):
                print(json.dumps(result))
                return
            if result is not None and result.get("backend") == "cpu":
                # JAX_PLATFORMS pinning should make this impossible, but
                # never report a hook-tainted CPU run as the accelerator.
                errors.append("tpu attempt silently ran on cpu backend")
                print(errors[-1], file=sys.stderr)
                break  # deterministic misconfiguration — retry won't help
            errors.append(
                f"tpu attempt {attempt + 1} rc={proc.returncode}: "
                f"{_tail(proc.stderr, 400)}"
            )
            print(errors[-1], file=sys.stderr)
            if proc.returncode == 124:  # run_child's watchdog timeout rc
                break  # hung to the deadline — don't stall another round

    # Attempt 2: CPU with the accelerator hook scrubbed — always possible.
    env = hz.scrubbed_cpu_env()
    env.update(child_marker)
    proc = hz.run_child(argv, env, CPU_ATTEMPT_TIMEOUT_S)
    result = _parse_child_json(proc.stdout)
    if proc.returncode == 0 and result is not None:
        if errors:
            result["backend"] = "cpu-fallback"
            result["note"] = "; ".join(errors)
        print(json.dumps(result))
        return
    errors.append(
        f"cpu attempt rc={proc.returncode}: {_tail(proc.stderr, 400)}"
    )
    print(errors[-1], file=sys.stderr)

    # Hard failure: still emit a parseable line so the round records the
    # error itself rather than a traceback.
    print(
        json.dumps(
            {
                "metric": "consensus_throughput_bacterial",
                "value": 0.0,
                "unit": "Mbases/s",
                "vs_baseline": 0.0,
                "backend": "failed",
                "note": "; ".join(errors),
            }
        )
    )
    sys.exit(1)


if __name__ == "__main__":
    if os.environ.get("KINDEL_TPU_BENCH_CHILD"):
        print(json.dumps(_run_benchmark()))
    else:
        main()
